"""Delayed-label join: outcome records meet logged predictions.

Fraud labels arrive days after scoring (the chargeback window) — live
model quality is only measurable by JOINING outcomes back onto the
predictions the score log sampled.  :class:`OutcomeJoiner` holds the
sampled predictions in a bounded in-memory window keyed by request id;
outcome records arrive either through ``POST /outcome`` on the serve
port or as JSONL files in a drop directory
(``<modelset>/telemetry/outcomes/`` — the batch path for an offline
label feed), and each join hands ``(generation, scores, labels)`` to
the quality monitor.

The window is a WATERMARK (``-Dshifu.quality.watermarkS``): predictions
older than the watermark are evicted, and outcomes for evicted or
never-sampled requests are counted ``late`` and dropped — the join is
bounded in memory and honest about sampling (a sampled score log can
only ever join the fraction it kept).
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from . import registry

log = logging.getLogger(__name__)

OUTCOMES_DIRNAME = "outcomes"

DEFAULT_WATERMARK_S = 3600.0


def outcomes_drop_dir(model_set_dir: str) -> str:
    return os.path.join(model_set_dir, "telemetry", OUTCOMES_DIRNAME)


def outcome_watermark_s(override: Optional[float] = None) -> float:
    """``-Dshifu.quality.watermarkS`` — the join window: outcomes for
    predictions older than this are late."""
    if override is not None:
        return float(override)
    from ..config import environment
    p = environment.get_property("shifu.quality.watermarkS")
    if p is not None:
        try:
            return float(p)
        except (TypeError, ValueError):
            pass
    return DEFAULT_WATERMARK_S


class OutcomeJoiner:
    """Request-id join of delayed outcomes onto sampled predictions.

    ``record_prediction`` is fed by the score log's ``on_log`` hook (so
    only SAMPLED predictions are joinable — the contract).  A repeated
    request id concatenates scores (a burst split across launches).
    ``on_join`` receives ``(gen, scores, labels)`` per successful join.
    """

    def __init__(self, watermark_s: Optional[float] = None,
                 on_join: Optional[Callable] = None,
                 clock: Callable[[], float] = time.time):
        self.watermark_s = outcome_watermark_s(watermark_s)
        self.on_join = on_join
        self._clock = clock
        # req -> [first_ts, gen, [score chunks]]; insertion order is
        # arrival order, so eviction pops from the front
        self._pending: "OrderedDict[str, list]" = OrderedDict()
        self.stats: Dict[str, int] = {"predictions": 0, "outcomes": 0,
                                      "joined_rows": 0, "late": 0,
                                      "evicted": 0, "malformed": 0}

    # ------------------------------------------------------------ feeding
    def record_prediction(self, req: str, scores, gen: int,
                          ts: Optional[float] = None) -> None:
        now = self._clock() if ts is None else float(ts)
        chunk = np.asarray(scores, np.float32).ravel()
        ent = self._pending.get(req)
        if ent is not None:
            ent[2].append(chunk)
        else:
            self._pending[req] = [now, int(gen), [chunk]]
        self.stats["predictions"] += 1
        self._evict(now)

    def _evict(self, now: float) -> None:
        horizon = now - self.watermark_s
        while self._pending:
            first = next(iter(self._pending))
            if self._pending[first][0] >= horizon:
                break
            del self._pending[first]
            self.stats["evicted"] += 1

    # ------------------------------------------------------------ joining
    def add_outcome(self, req: str, labels, ts: Optional[float] = None
                    ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """Join one outcome record; returns ``(gen, scores, labels)`` or
        ``None`` (unknown/evicted request id, watermark miss, or a
        label/score length mismatch — all counted)."""
        now = self._clock() if ts is None else float(ts)
        self.stats["outcomes"] += 1
        registry.counter("quality.outcomes").inc()
        ent = self._pending.pop(req, None)
        if ent is None or now - ent[0] > self.watermark_s:
            self.stats["late"] += 1
            registry.counter("quality.outcomes_late").inc()
            return None
        scores = np.concatenate(ent[2])
        lab = np.asarray(labels, np.float32).ravel()
        if lab.size == 1 and scores.size > 1:
            lab = np.full(scores.shape, float(lab[0]), np.float32)
        if len(lab) != len(scores):
            self.stats["malformed"] += 1
            registry.counter("quality.outcomes_late").inc()
            return None
        self.stats["joined_rows"] += int(len(scores))
        if self.on_join is not None:
            self.on_join(ent[1], scores, lab)
        return ent[1], scores, lab

    # ----------------------------------------------------------- drop dir
    def ingest_drop_dir(self, path: str) -> int:
        """Consume outcome files (JSONL, one ``{"req", "labels"}`` per
        line; a ``{"outcomes": [...]}`` wrapper line is unrolled) from
        the drop directory; files are removed after ingest, torn lines
        counted malformed.  Returns records processed."""
        if not os.path.isdir(path):
            return 0
        n = 0
        for name in sorted(os.listdir(path)):
            if not (name.endswith(".json") or name.endswith(".jsonl")):
                continue
            full = os.path.join(path, name)
            try:
                with open(full) as f:
                    lines = f.readlines()
            except OSError:             # pragma: no cover
                log.warning("outcome drop file unreadable: %s", full,
                            exc_info=True)
                continue
            for line in lines:
                if not line.strip():
                    continue
                try:
                    doc = json.loads(line)
                except ValueError:
                    self.stats["malformed"] += 1
                    continue
                recs = doc.get("outcomes", [doc]) \
                    if isinstance(doc, dict) else []
                for rec in recs:
                    try:
                        self.add_outcome(str(rec["req"]),
                                         rec.get("labels",
                                                 rec.get("label")))
                        n += 1
                    except (KeyError, TypeError, ValueError):
                        self.stats["malformed"] += 1
            try:
                os.remove(full)
            except OSError:             # pragma: no cover
                pass
        return n

    @property
    def pending(self) -> int:
        return len(self._pending)
