"""Metric-name manifest — the ONE registry of declared instrument names.

Every ``obs.counter("...")`` / ``obs.gauge("...")`` /
``obs.histogram("...")`` call site anywhere in ``shifu_tpu/`` must name a
metric declared here (or start with a declared dynamic-family prefix).
A lint-style test (``tests/test_obs_plane.py``) greps the source tree
and enforces it, because the registry's create-on-first-use convenience
has a failure mode that is otherwise silent: a typo'd name at one call
site quietly creates a NEW metric, the dashboards / bench joins keep
reading the old (now frozen) one, and nothing errors anywhere.

Declaring a metric: ``MANIFEST[name] = (type, help)``.  Families whose
member names are data-dependent (per-eval-set AUC, bench extras) declare
a prefix in ``PREFIXES`` instead — f-string call sites must start with
one of them.

SPAN names get the same treatment (``SPANS`` / ``SPAN_PREFIXES``): the
timeline/report joins key on span-name literals, so a typo'd span name
would silently vanish from every report.  Root spans named after the
step (``obs.span(self.profile_name, ...)``) are variables, not
literals, and ride outside the lint.
"""

from __future__ import annotations

from typing import Dict, Tuple

# name -> (instrument type, one-line help)
MANIFEST: Dict[str, Tuple[str, str]] = {
    # ---- ingest plane (spill cache / window prep / H2D pipeline)
    "ingest.bytes_read": ("counter", "bytes materialized into windows"),
    "ingest.windows_emitted": ("counter", "windows yielded to consumers"),
    "ingest.rows_emitted": ("counter", "valid rows in emitted windows"),
    "ingest.h2d_wait_seconds": ("counter",
                                "consumer time blocked on window prep/H2D"),
    "ingest.disk_passes": ("counter", "full/tail stream traversals"),
    "ingest.spill_hits": ("counter", "sweeps served from the mmap spill"),
    "ingest.spill_misses": ("counter", "sweeps that re-read npz shards"),
    "ingest.retries": ("counter", "transient IO errors absorbed by retry"),
    "ingest.rows_padded": ("counter",
                           "zero-weight pad rows added to fill windows"),
    "ingest.parse_stall_frac": ("gauge",
                                "fraction of the parse-pool consumer "
                                "loop spent blocked on parse futures "
                                "(~0 = parse hidden, ~1 = parse-bound)"),
    # ---- one-parse raw cache (data/rawcache)
    "rawcache.hits": ("counter",
                      "raw passes served from the columnar raw cache "
                      "(zero string-plane touch)"),
    "rawcache.misses": ("counter",
                        "raw passes that parsed the string plane with "
                        "a cache root configured"),
    "rawcache.bytes_written": ("counter",
                               "decoded-column bytes committed into "
                               "the raw cache"),
    # ---- data hygiene
    "data.quarantined_rows": ("counter", "rows quarantined as unreadable"),
    "data.quarantined_shards": ("counter", "shards quarantined as torn"),
    # ---- stats plane
    "stats.rows": ("counter", "rows swept by the stats accumulators"),
    "stats.columns": ("gauge", "columns in the stats sweep"),
    "stats.rows_per_sec": ("gauge", "stats sweep throughput"),
    "stats.resumed_chunks": ("counter", "chunks skipped via mid-sweep resume"),
    # ---- norm plane
    "norm.rows": ("counter", "rows materialized by norm"),
    "norm.shards": ("gauge", "shards written by norm"),
    "norm.rows_per_sec": ("gauge", "norm throughput"),
    "norm.resumed_shards": ("counter", "committed shards verified on resume"),
    # ---- train plane
    "train.epochs": ("counter", "epochs completed (NN/LR/WDL/SVM)"),
    "train.epoch_s": ("histogram", "per-epoch wall-clock"),
    "train.trees": ("counter", "trees built (GBT/RF/DT)"),
    "train.trees_built": ("gauge", "final forest size of the last trainer"),
    "train.valid_err": ("gauge", "last validation error"),
    "train.host_syncs": ("counter", "device->host value-forcing fetches"),
    "train.tail_sweeps": ("counter", "disk-tail re-streams paid"),
    "train.tail_repairs": ("counter", "c2f speculation repairs"),
    "train.tail_repair_levels": ("counter", "levels regrown by repairs"),
    "train.tail_c2f_fallbacks": ("counter",
                                 "c2f auto-fallbacks to the exact schedule"),
    # ---- WDL sharded categorical plane (train/wdl_shard)
    "wdl.shard_devices": ("gauge", "data-axis shards each WDL table "
                                   "splits over"),
    "wdl.shard_table_bytes": ("gauge", "per-device bytes of table params "
                                       "+ optimizer moments"),
    "wdl.hash_buckets": ("gauge", "hashed-ID bucket space (0 = exact ids)"),
    "wdl.hashed_cols": ("gauge", "categorical columns on the hashed-ID "
                                 "path"),
    "wdl.serve_shard_devices": ("gauge", "devices the serve-time sharded "
                                         "table copy spans"),
    # ---- eval plane (per-set AUC gauges ride the eval. prefix)
    "eval.rows_scored": ("counter", "eval rows scored"),
    "eval.rows_per_sec": ("gauge", "eval scoring throughput"),
    # ---- varselect plane
    "varsel.host_syncs": ("counter", "varselect packed fetches"),
    "varsel.mask_batches": ("counter", "mask-batched programs dispatched"),
    "varsel.windows": ("counter", "windows swept by varselect"),
    "varsel.rows_per_sec": ("gauge", "varselect throughput"),
    "varsel.candidates": ("gauge", "candidate columns scored"),
    # ---- device / XLA accounting (registry-internal writers)
    "device.bytes_in_use": ("gauge", "HBM in use (high-water sampled)"),
    "device.peak_bytes_in_use": ("gauge", "HBM peak"),
    "device.bytes_limit": ("gauge", "HBM capacity"),
    "xla.compile_count": ("counter", "XLA compilations observed"),
    "xla.compile_time_s": ("counter", "XLA compile wall-clock"),
    # ---- cost-attribution plane (obs/costs)
    "xla.recompiles": ("counter",
                       "costed executables rebuilt for a NEW input "
                       "signature (the shape-churn sentinel)"),
    "xla.launches": ("counter", "costed executable launches"),
    # ---- serving plane (serve/)
    "serve.requests": ("counter",
                       "scoring requests accepted (one per submit; "
                       "row volume is serve.rows_scored)"),
    "serve.rows_scored": ("counter", "request rows scored"),
    "serve.batches": ("counter", "padded-bucket device launches"),
    "serve.rows_padded": ("counter",
                          "pad rows added to fill serve buckets"),
    "serve.flush_full": ("counter", "flushes triggered by a full bucket"),
    "serve.flush_deadline": ("counter",
                             "flushes triggered by the maxDelayMs "
                             "deadline"),
    "serve.request_errors": ("counter", "batches failed in-flight"),
    "serve.swaps": ("counter", "model hot-swaps promoted"),
    "serve.rollbacks": ("counter",
                        "registry re-flips to the previous generation "
                        "(probation failure or operator rollback)"),
    "serve.trace_sampled": ("counter",
                            "requests head-sampled into per-request "
                            "tracing (shifu.serve.traceSampleRate)"),
    "serve.queue_depth": ("gauge",
                          "rows currently queued (set at each flush and "
                          "sampled into SERVE heartbeats/healthz — the "
                          "queue-buildup early warning)"),
    "serve.bucket_occupancy": ("histogram",
                               "real rows / bucket size per launch "
                               "(p50/p99 land in metrics.prom; was a "
                               "last-batch-only gauge before round 12)"),
    "serve.bucket_rungs_added": ("counter",
                                 "ladder rungs added by occupancy-"
                                 "driven refinement (compiled ahead of "
                                 "use)"),
    "serve.batch_latency_ms": ("histogram",
                               "oldest-request latency per batch"),
    # ---- overload protection (serve/overload; bounded admission +
    # deadline shedding + brownout)
    "serve.shed_overload": ("counter",
                            "submits rejected at the maxQueueRows "
                            "admission cap (coded 429/overloaded)"),
    "serve.shed_expired": ("counter",
                           "queued requests shed because their deadline "
                           "passed before pad/launch (coded 504)"),
    "serve.cancelled": ("counter",
                        "client-abandoned tickets (wait timed out) shed "
                        "from the queue before launch"),
    "serve.mode": ("gauge",
                   "serving mode: 0 normal, 1 brownout (degraded under "
                   "sustained burn/queue stress)"),
    "serve.brownouts": ("counter", "brownout-mode entries (lifetime)"),
    # ---- raw-record serving (serve/transform fused into the scorer)
    "serve.raw_requests": ("counter",
                           "raw-record scoring requests accepted "
                           "(POST /score with records)"),
    "serve.raw_rows": ("counter",
                       "raw records parsed and scored through the "
                       "fused-transform executable"),
    "serve.raw_rejects": ("counter",
                          "malformed raw records rejected per-record "
                          "with a coded error (the rest of the request "
                          "still scores)"),
    # ---- serving fleet (serve/router)
    "serve.fleet_replicas_up": ("gauge",
                                "replicas in rotation after the last "
                                "health sweep"),
    "serve.fleet_requeues": ("counter",
                             "requests requeued on a peer after a "
                             "replica died mid-flight"),
    "serve.fleet_drains": ("counter",
                           "replicas pulled from rotation (SLO burn, "
                           "stale heartbeat, or death)"),
    "serve.fleet_swaps": ("counter",
                          "coordinated fleet-wide hot-swaps driven "
                          "through the router"),
    "serve.fleet_hedges": ("counter",
                           "hedged second dispatches fired after the "
                           "p99 hedge delay (first response wins)"),
    "serve.fleet_breaker_opens": ("counter",
                                  "replica circuit breakers opened on "
                                  "consecutive transport/5xx failures"),
    "serve.fleet_retry_denied": ("counter",
                                 "requeues shed because the retry "
                                 "budget was exhausted (coded 429)"),
    # ---- live SLO plane (obs/slo; mirrored into metrics.prom each beat)
    "slo.p50_ms": ("gauge", "sliding-window latency p50 (log sketch)"),
    "slo.p99_ms": ("gauge", "sliding-window latency p99 (log sketch)"),
    "slo.availability": ("gauge", "observed availability over the ring"),
    "slo.burn_rate_short": ("gauge",
                            "max error-budget burn over the short "
                            "(current-window) horizon"),
    "slo.burn_rate_long": ("gauge",
                           "max error-budget burn over the long "
                           "(whole-ring) horizon"),
    "slo.alerts_firing": ("gauge", "burn-rate alert rules currently firing"),
    # ---- elastic DCN plane (parallel/elastic, parallel/mesh)
    "dcn.connect_retries": ("counter",
                            "coordinator connect failures absorbed by "
                            "the bounded backoff ladder"),
    "dcn.steps_closed": ("counter", "elastic steps this controller "
                                    "closed (won the exclusive commit)"),
    "dcn.step_timeouts": ("counter",
                          "elastic steps closed on stepTimeoutMs with "
                          "stragglers outstanding"),
    "dcn.step_wait_seconds": ("counter",
                              "time blocked waiting for quorum/close "
                              "(the straggler-masking cost)"),
    "dcn.late_applied": ("counter",
                         "late contributions folded into a later close "
                         "within the staleness window"),
    "dcn.late_dropped": ("counter",
                         "late contributions dropped past the staleness "
                         "window (quorum mode drops all)"),
    "dcn.catchup_steps": ("counter",
                          "steps replayed from the close journal "
                          "instead of recomputed"),
    "dcn.rejoins": ("counter",
                    "controller restarts that rejoined a live job "
                    "(incarnation > 1)"),
    "dcn.membership_epoch": ("gauge",
                             "current membership epoch (bumps on "
                             "join/leave/rejoin)"),
    "dcn.live_members": ("gauge",
                         "controllers the heartbeat staleness rule "
                         "considers alive"),
    # ---- continual refresh plane (refresh/)
    "refresh.triggers": ("counter",
                         "refresh cycles started (PSI breach or "
                         "schedule)"),
    "refresh.skips": ("counter",
                      "triggers suppressed by the cooldown guard"),
    "refresh.retrains": ("counter", "warm retrains run"),
    "refresh.promotions": ("counter",
                           "candidates hot-swapped into serving after "
                           "passing the AUC gate"),
    "refresh.rejections": ("counter",
                           "candidates archived on AUC regression "
                           "(incumbent stays live)"),
    "refresh.rollbacks": ("counter",
                          "promotions rolled back in probation (SLO "
                          "burn / canary parity)"),
    "refresh.state": ("gauge",
                      "controller state: 0 idle, 1 training, "
                      "2 probation"),
    "refresh.generation": ("gauge", "serving generation under refresh"),
    "refresh.cycle": ("gauge", "refresh cycles begun (lifetime)"),
    # ---- drift monitor (obs/drift)
    "drift.rows": ("gauge", "rows folded into the live drift counts"),
    "drift.columns_tracked": ("gauge", "columns with a training snapshot"),
    "drift.columns_flagged": ("gauge", "columns with PSI over threshold"),
    "drift.psi_max": ("gauge", "max per-column PSI vs training snapshot"),
    "drift.psi_mean": ("gauge", "mean per-column PSI vs training snapshot"),
    # ---- model-quality plane (obs/scorelog, obs/outcomes, obs/quality)
    "scorelog.records": ("counter",
                         "sampled prediction records appended to the "
                         "score log"),
    "scorelog.segments": ("counter",
                          "score-log segments committed by atomic "
                          "rotation"),
    "scorelog.pruned_segments": ("counter",
                                 "committed segments pruned by the "
                                 "disk budget"),
    "quality.outcomes": ("counter",
                         "outcome records ingested (POST /outcome + "
                         "drop directory)"),
    "quality.outcomes_late": ("counter",
                              "outcomes dropped: unknown/evicted "
                              "request id, watermark miss, or length "
                              "mismatch"),
    "quality.scored_rows": ("gauge",
                            "sampled scores folded into the live "
                            "score histograms"),
    "quality.joined_rows": ("gauge",
                            "outcome-joined (score,label) rows in the "
                            "rolling windows"),
    "quality.live_auc": ("gauge",
                         "rolling live AUC of the current serving "
                         "generation"),
    "quality.ece": ("gauge",
                    "reliability-bin expected calibration error "
                    "(current generation)"),
    "quality.score_psi": ("gauge",
                          "PSI of live scores vs the posttrain "
                          "snapshot (current generation)"),
    "quality.degraded": ("gauge",
                         "1 while the quality plane flags live-AUC or "
                         "score-PSI degradation"),
}

# dynamic families: f-string names must start with one of these
PREFIXES: Tuple[str, ...] = (
    "bench.",        # per-plane bench gauges mirror BENCH_r0N extras
    "eval.",         # eval.<set>.auc / eval.<set>.pr_auc per eval set
)

# span-name literals (obs.span("...") / obs.record_span("...") call
# sites) — the timeline tracks, report sections and tests join on these
SPANS: Dict[str, str] = {
    "setup": "step scaffolding before process() (processor base)",
    "process": "step body (processor base)",
    "varselect.sensitivity": "SE/ST sensitivity scoring phase",
    "ingest.window_prep": "background window materialization (prep thread)",
    "ingest.h2d_wait": "consumer blocked on window prep / H2D",
    "serve.request": ("sampled scoring request: queue-wait / deadline-"
                      "wait / pad / launch / device decomposition"),
    "serve.batch": ("sampled padded-bucket launch; links the member "
                    "requests' trace ids (fan-in causality)"),
    "dcn.step": ("elastic quorum step: contribute -> wait for quorum/"
                 "timeout/peer close -> adopt the committed aggregate"),
    "refresh.retrain": ("warm-start retraining of a refresh candidate "
                        "(checkpoint resume over the data-window "
                        "cursor)"),
}

# span families whose names embed data (the bench's per-plane spans)
SPAN_PREFIXES: Tuple[str, ...] = (
    "bench.",
)


def is_declared(name: str) -> bool:
    return name in MANIFEST or any(name.startswith(p) for p in PREFIXES)


def is_declared_span(name: str) -> bool:
    return name in SPANS or any(name.startswith(p) for p in SPAN_PREFIXES)


def declared_type(name: str) -> str:
    """Instrument type for an exact declared name ('' for prefix-only)."""
    if name in MANIFEST:
        return MANIFEST[name][0]
    return ""
