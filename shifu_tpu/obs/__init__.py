"""Pipeline-wide observability: span tracing, metrics, profiler hooks.

The TPU-native replacement for the reference's Hadoop/YARN counters and
Guagua master logs (``ShifuCLI`` step timing lines, MR job counters): one
in-process telemetry layer every step processor, trainer, and plane
reports through, with a JSONL sink under ``<modelset>/telemetry/`` and a
CLI report surface (``shifu-tpu analysis --telemetry``).

Four modules:

- :mod:`tracer` — nested wall-clock spans (optionally
  ``jax.block_until_ready``-fenced) + point events, thread-safe
  collector, JSONL sink;
- :mod:`registry` — named counters/gauges/histograms (rows, epochs,
  loss, throughput, device-memory high-water, XLA compile accounting);
- :mod:`profiler` — opt-in ``jax.profiler.trace()`` capture around any
  step (``shifu-tpu <step> --profile [dir]``);
- :mod:`report` — renders the last run's spans/metrics as a tree with
  per-step self-time and rows/sec.

Everything is ZERO-COST when disabled (the default): ``span()`` returns
a shared no-op singleton, instruments are no-op singletons, no fencing,
no files.  Enable with env ``SHIFU_TPU_TELEMETRY=1``, property
``-Dshifu.telemetry=on``, or the per-step ``--telemetry`` flag.
"""

from .registry import (counter, gauge, histogram,             # noqa: F401
                       sample_device_memory, ensure_compile_listener,
                       snapshot, get_registry)
from .tracer import (SCHEMA_VERSION, enabled, set_enabled,    # noqa: F401
                     fencing_enabled, span, event, fence, flush,
                     pending_records, reset_for_tests)
