"""Pipeline-wide observability: tracing, metrics, health, drift, export.

The TPU-native replacement for the reference's Hadoop/YARN counters and
Guagua master logs (``ShifuCLI`` step timing lines, MR job counters,
per-worker progress RPC): one in-process telemetry layer every step
processor, trainer, and plane reports through, with a JSONL sink under
``<modelset>/telemetry/`` and live + post-hoc CLI surfaces
(``shifu-tpu monitor``, ``shifu-tpu analysis --telemetry [--timeline]``).

Modules:

- :mod:`tracer` — nested wall-clock spans (optionally
  ``jax.block_until_ready``-fenced) + point events, thread-safe
  collector with a live-span registry, JSONL sink;
- :mod:`registry` — named counters/gauges/histograms (rows, epochs,
  loss, throughput, device-memory high-water, XLA compile accounting);
  instruments are thread-safe (ingest prep thread + trainers + the
  heartbeat/exporter readers share them);
- :mod:`manifest` — THE declaration of every metric name (a lint test
  enforces it: a typo'd name cannot silently mint a new metric);
- :mod:`health` — per-process heartbeat files under
  ``<modelset>/telemetry/health/`` (atomic, background thread) with
  live step/phase/progress and a staleness model;
- :mod:`monitor` — the ``shifu-tpu monitor`` renderer tailing those
  heartbeats (stale/stalled/straggler flags, quorum summary);
- :mod:`timeline` — span JSONL -> Chrome/Perfetto ``trace_event`` JSON,
  ingest-thread spans on their own track;
- :mod:`exporter` — periodic OpenMetrics-text + JSON registry snapshots
  (``telemetry/metrics.prom`` / ``metrics.json``);
- :mod:`slo` — live SLO plane for the serving path: sliding-window
  latency quantiles from a fixed-bin log histogram sketch (no
  per-request storage), availability tracking and multi-window
  error-budget burn-rate alerts against declared objectives
  (``-Dshifu.serve.sloP99Ms`` / ``-Dshifu.serve.sloAvailability``),
  surfaced via ``/slo``, SERVE heartbeats and ``metrics.prom``;
- :mod:`drift` — streaming per-column PSI of live binned windows vs the
  training-time ColumnConfig snapshot (ROADMAP #5's promotion signal);
- :mod:`scorelog` — sampled, bounded prediction logging from the serve
  path (crash-safe append-only segments with atomic rotation and a
  disk budget under ``<modelset>/telemetry/scorelog/``);
- :mod:`outcomes` — delayed-label join: outcome records (``POST
  /outcome`` or a drop directory) meet logged predictions by request
  id inside a bounded watermark window;
- :mod:`quality` — streaming model-quality monitor: per-generation
  live AUC / reliability-bin calibration over joined windows +
  score-distribution PSI vs the ``posttrain.json`` training snapshot
  (the refresh controller's third trigger source);
- :mod:`profiler` — opt-in ``jax.profiler.trace()`` capture around any
  step (``shifu-tpu <step> --profile [dir]``);
- :mod:`report` — renders the last run's spans/metrics as a tree with
  per-step self-time, rows/sec, ingest-stall / tail / drift sections;
- :mod:`costs` — device cost attribution: ``costed_jit`` captures
  FLOPs / bytes / memory per named executable, counts compiles,
  launches and RECOMPILES (the shape-churn sentinel), analytic models
  cover Pallas kernels XLA cannot see through;
- :mod:`utilization` — joins executable costs against span wall times:
  achieved FLOP/s, bytes/s, percent-of-peak and a roofline verdict per
  plane (``analysis --telemetry --utilization``).

Everything is ZERO-COST when disabled (the default): ``span()`` returns
a shared no-op singleton, instruments are no-op singletons, heartbeat /
exporter / drift factories return ``None``, no threads, no fencing, no
files.  Enable with env ``SHIFU_TPU_TELEMETRY=1``, property
``-Dshifu.telemetry=on``, or the per-step ``--telemetry`` flag.
"""

from .registry import (counter, gauge, histogram,             # noqa: F401
                       sample_device_memory, ensure_compile_listener,
                       snapshot, get_registry)
from .tracer import (SCHEMA_VERSION, enabled, set_enabled,    # noqa: F401
                     fencing_enabled, span, event, fence, flush,
                     record_span, pending_records, live_spans,
                     reset_for_tests)
from .manifest import (MANIFEST, PREFIXES, SPANS,             # noqa: F401
                       SPAN_PREFIXES, is_declared, is_declared_span)
from .slo import (SLOTracker, LogBins, LOG_BINS,              # noqa: F401
                  quantile_from_counts, slo_objectives,
                  BrownoutGovernor)
from .health import (HeartbeatWriter, start_heartbeat,        # noqa: F401
                     read_health, classify, health_dir_for,
                     heartbeat_interval_s)
from .exporter import (MetricsExporter, start_exporter,       # noqa: F401
                       render_openmetrics, write_metrics_files,
                       metric_name)
from .drift import (DriftMonitor, start_drift_monitor,        # noqa: F401
                    psi_threshold)
from .scorelog import (ScoreLog, read_score_records,          # noqa: F401
                       scorelog_dir, scorelog_sample_rate)
from .outcomes import (OutcomeJoiner, outcomes_drop_dir,      # noqa: F401
                       outcome_watermark_s)
from .quality import (QualityMonitor, start_quality_monitor,  # noqa: F401
                      write_posttrain_snapshot,
                      load_posttrain_snapshot,
                      posttrain_snapshot_path, quality_artifact_path)
from .costs import (costed_jit, record_executable,            # noqa: F401
                    register_cost_model, record_model_launch,
                    cost_snapshot, resolve_peaks, backend_info)

__all__ = [
    # tracer
    "SCHEMA_VERSION", "enabled", "set_enabled", "fencing_enabled",
    "span", "event", "fence", "flush", "record_span", "pending_records",
    "live_spans", "reset_for_tests",
    # registry
    "counter", "gauge", "histogram", "sample_device_memory",
    "ensure_compile_listener", "snapshot", "get_registry",
    # manifest
    "MANIFEST", "PREFIXES", "SPANS", "SPAN_PREFIXES", "is_declared",
    "is_declared_span",
    # SLO plane
    "SLOTracker", "LogBins", "LOG_BINS", "quantile_from_counts",
    "slo_objectives", "BrownoutGovernor",
    # health / monitor plane
    "HeartbeatWriter", "start_heartbeat", "read_health", "classify",
    "health_dir_for", "heartbeat_interval_s",
    # exporter
    "MetricsExporter", "start_exporter", "render_openmetrics",
    "write_metrics_files", "metric_name",
    # drift
    "DriftMonitor", "start_drift_monitor", "psi_threshold",
    # model-quality plane
    "ScoreLog", "read_score_records", "scorelog_dir",
    "scorelog_sample_rate", "OutcomeJoiner", "outcomes_drop_dir",
    "outcome_watermark_s", "QualityMonitor", "start_quality_monitor",
    "write_posttrain_snapshot", "load_posttrain_snapshot",
    "posttrain_snapshot_path", "quality_artifact_path",
    # cost-attribution plane
    "costed_jit", "record_executable", "register_cost_model",
    "record_model_launch", "cost_snapshot", "resolve_peaks",
    "backend_info",
]
