"""Streaming drift monitor — per-column PSI of live windows vs training.

Fraud distributions drift; the reference treats PSI as a first-class
stat (``udf/PSICalculatorUDF``, the ``stats -psi`` unit sweep).  This
module makes it a LIVE signal: :class:`DriftMonitor` is seeded with the
training-time binning snapshot (the per-bin counts ``stats`` wrote into
``ColumnConfig.json`` — ``binCountNeg``/``binCountPos``, missing bin
last) and accumulates the SAME per-column bin counts incrementally from
whatever binned windows flow past it (norm re-runs on new data windows,
eval sets, the refresh stream), so

    PSI(training snapshot, everything seen so far)

is available at any moment, computed by the exact batch formula
(:func:`shifu_tpu.ops.stats_math.psi` — counts are additive, so the
incremental accumulation IS the batch computation) at per-window cost of
one ``np.add.at`` over a packed (column, bin) space.

This is ROADMAP #5's promotion signal: the eval-gated refresh reads
``drift.psi_max`` / the per-column table to decide whether a retrain is
warranted, and ``analysis --telemetry`` renders the same table from the
``drift.json`` artifact.

Zero-cost when telemetry is disabled: :func:`start_drift_monitor`
returns ``None`` and the pipeline call sites skip the per-window update
entirely.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..ioutil import atomic_write_json
from ..ops.stats_math import psi
from . import registry, tracer

log = logging.getLogger(__name__)

DRIFT_BASENAME = "drift.json"

# industry-standard PSI bands: < 0.1 stable, 0.1-0.25 drifting, > 0.25
# act (retrain) — the default flag threshold, property-overridable
DEFAULT_PSI_THRESHOLD = 0.25


def psi_threshold(override: Optional[float] = None) -> float:
    if override is not None:
        return float(override)
    from ..config import environment
    p = environment.get_property("shifu.drift.psiThreshold")
    if p is not None:
        try:
            return float(p)
        except (TypeError, ValueError):
            pass
    return DEFAULT_PSI_THRESHOLD


class DriftMonitor:
    """Incremental per-column PSI vs the ColumnConfig binning snapshot.

    ``columns`` is the bin-index space of the windows that will be fed in
    (the transformer's model-input columns, in order): ``update(bins)``
    expects ``bins[:, j]`` to hold column ``columns[j]``'s bin index in
    ``0..num_bins`` (missing = ``num_bins``) — exactly the
    ``TransformedChunk.bins`` / clean-plane layout.  Columns whose
    snapshot has no per-bin counts (stats not run, or a meta/target
    column) are carried as NaN and never flagged.
    """

    def __init__(self, columns: Sequence, threshold: Optional[float] = None):
        self.columns = list(columns)
        self.threshold = psi_threshold(threshold)
        nb, expected = [], []
        self._have = np.zeros(len(self.columns), bool)
        for j, cc in enumerate(self.columns):
            neg = cc.columnBinning.binCountNeg
            pos = cc.columnBinning.binCountPos
            n_bins = cc.num_bins() + 1          # + trailing missing bin
            exp = np.zeros(n_bins, np.float64)
            if neg is not None and pos is not None:
                m = min(n_bins, len(neg), len(pos))
                exp[:m] = (np.asarray(neg[:m], np.float64)
                           + np.asarray(pos[:m], np.float64))
                self._have[j] = exp.sum() > 0
            nb.append(n_bins)
            expected.append(exp)
        self._nb = np.asarray(nb, np.int64)
        self._offsets = np.concatenate(
            [[0], np.cumsum(self._nb)]).astype(np.int64)
        self._expected = expected
        self._counts = np.zeros(int(self._offsets[-1]), np.float64)
        self.rows = 0
        self.windows = 0

    # ------------------------------------------------------------ updates
    def update(self, bins: np.ndarray,
               weights: Optional[np.ndarray] = None) -> None:
        """Fold one binned window ``[R, C]`` into the live counts (rows
        with zero weight — a streamed window's padding — are excluded)."""
        bins = np.asarray(bins)
        if bins.ndim != 2 or bins.shape[1] != len(self.columns):
            raise ValueError(
                f"drift window has {bins.shape[1:]} columns, monitor "
                f"tracks {len(self.columns)}")
        if weights is not None:
            keep = np.asarray(weights) > 0
            bins = bins[keep]
        if not len(bins):
            return
        # pack (column, bin) into one flat axis: a single bincount pass
        # per window regardless of column count (the stats -psi recipe)
        idx = np.minimum(np.asarray(bins, np.int64), self._nb - 1) \
            + self._offsets[:-1]
        self._counts += np.bincount(idx.ravel(),
                                    minlength=len(self._counts))
        self.rows += int(len(bins))
        self.windows += 1

    # ------------------------------------------------------------ read-out
    def column_psi(self) -> np.ndarray:
        """Per-column PSI (NaN where the snapshot has no counts or no
        live rows have been seen)."""
        out = np.full(len(self.columns), np.nan)
        if self.rows == 0:
            return out
        for j in range(len(self.columns)):
            if not self._have[j]:
                continue
            s, e = self._offsets[j], self._offsets[j + 1]
            out[j] = float(psi(self._expected[j], self._counts[s:e]))
        return out

    def summary(self) -> Dict[str, Any]:
        vals = self.column_psi()
        ok = ~np.isnan(vals)
        flagged = [self.columns[j].columnName
                   for j in np.flatnonzero(ok & (vals > self.threshold))]
        return {
            "kind": "drift",
            "schema_version": tracer.SCHEMA_VERSION,
            "ts": round(time.time(), 3),
            "rows": self.rows,
            "windows": self.windows,
            "threshold": self.threshold,
            "psi_max": float(np.nanmax(vals)) if ok.any() else None,
            "psi_mean": float(np.nanmean(vals)) if ok.any() else None,
            "flagged": flagged,
            "columns": {
                self.columns[j].columnName: round(float(vals[j]), 6)
                for j in np.flatnonzero(ok)},
        }

    def emit(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Publish: ``drift.*`` gauges into the registry (scraped by the
        exporter, rendered by ``analysis --telemetry``) and, when
        ``path`` is given, the full per-column table as ``drift.json``
        (atomic)."""
        summ = self.summary()
        registry.gauge("drift.rows").set(self.rows)
        registry.gauge("drift.columns_tracked").set(int(self._have.sum()))
        registry.gauge("drift.columns_flagged").set(len(summ["flagged"]))
        if summ["psi_max"] is not None:
            registry.gauge("drift.psi_max").set(summ["psi_max"])
            registry.gauge("drift.psi_mean").set(summ["psi_mean"])
        if path:
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                atomic_write_json(path, summ)
            except OSError:
                log.warning("drift table write failed", exc_info=True)
        return summ


def start_drift_monitor(columns: Sequence,
                        threshold: Optional[float] = None
                        ) -> Optional[DriftMonitor]:
    """A monitor over the transformer's column list — ``None`` when
    telemetry is disabled (call sites skip their per-window update)."""
    if not tracer.enabled():
        return None
    mon = DriftMonitor(columns, threshold=threshold)
    if not mon._have.any():
        return None                  # nothing to compare against yet
    return mon
