"""Live SLO plane — sliding-window latency quantiles, availability and
multi-window error-budget burn-rate alerts for the serving path.

The serving contract is a latency/availability OBJECTIVE, not a metric:
``-Dshifu.serve.sloP99Ms`` (default 2x the flush deadline — the
measured "deadline + one launch" p99 of a healthy server) and
``-Dshifu.serve.sloAvailability`` (default 0.999).  This module tracks
compliance LIVE with bounded memory:

- :class:`LogBins` / :class:`SLOTracker` — latency quantiles come from a
  fixed-bin LOG histogram sketch (128 bins over 10 us..100 s, ~6.6%
  relative error per bin), held in a ring of sliding windows.  NO
  per-request storage: at 1M+ QPS the tracker's state stays a few KB and
  an ``observe_batch`` is one vectorized bincount under a lock.
- **Burn rates** — the SRE error-budget formulation.  Each objective
  defines an allowed failure fraction (p99 objective -> 1% of requests
  may exceed it; availability 0.999 -> 0.1% may error); the burn rate is
  the observed failure fraction over that allowance (burn 1.0 = exactly
  spending the budget, 14.4 = the classic page threshold).  Alerts are
  MULTI-WINDOW: a rule fires only when the burn exceeds its threshold
  over BOTH the short horizon (the current window — fast detection) and
  the long horizon (the whole ring — flap suppression), so a hard breach
  trips within one window while a transient blip does not page.
- Surfaces: :meth:`SLOTracker.summary` backs the ``/slo`` endpoint,
  :meth:`SLOTracker.compact` rides SERVE heartbeats into
  ``shifu-tpu monitor``, and :meth:`SLOTracker.emit_gauges` mirrors the
  headline numbers into the ``slo.*`` registry gauges each beat so
  ``metrics.prom`` scrapes them.

The tracker itself is telemetry-independent (the SLO is the serving
contract whether or not tracing is on); only the gauge mirror is gated
on the obs enable, per the zero-cost convention.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

DEFAULT_AVAILABILITY = 0.999
# p99 objective means 1% of requests may exceed it — the latency
# budget's allowed failure fraction is fixed by the quantile, not a knob
LATENCY_BUDGET_FRAC = 0.01

# multi-window burn thresholds (severity, burn): the classic SRE pair —
# 14.4 burns a 30-day budget in 2 days (page), 6.0 in 5 days (ticket)
ALERT_RULES: Tuple[Tuple[str, float], ...] = (("page", 14.4),
                                              ("ticket", 6.0))


def slo_objectives(max_delay_ms: float) -> Tuple[float, float]:
    """(p99_ms, availability) objectives: properties
    ``shifu.serve.sloP99Ms`` / ``shifu.serve.sloAvailability``, with
    defaults 2x the flush deadline (deadline + one launch, the healthy
    low-load p99) and 0.999."""
    from ..config import environment
    p99 = environment.get_float("shifu.serve.sloP99Ms",
                                2.0 * float(max_delay_ms))
    avail = environment.get_float("shifu.serve.sloAvailability",
                                  DEFAULT_AVAILABILITY)
    return max(float(p99), 0.0), min(max(float(avail), 0.0), 1.0 - 1e-9)


class LogBins:
    """Fixed log-spaced bin edges over [10**lo_exp, 10**hi_exp) seconds
    plus an underflow and an overflow bin.  Shared by the SLO tracker
    and the registry histogram sketch, so every quantile in the system
    has the same resolution."""

    __slots__ = ("lo_exp", "hi_exp", "per_decade", "n", "_scale")

    def __init__(self, lo_exp: int = -5, hi_exp: int = 2,
                 per_decade: int = 18):
        self.lo_exp = lo_exp
        self.hi_exp = hi_exp
        self.per_decade = per_decade
        # bin 0 = underflow (v <= 10**lo_exp), bin n-1 = overflow
        self.n = (hi_exp - lo_exp) * per_decade + 2
        self._scale = float(per_decade) / math.log(10.0)

    def index(self, v: float) -> int:
        if not v > 10.0 ** self.lo_exp:
            return 0
        i = int(math.log(v) * self._scale - self.lo_exp * self.per_decade) + 1
        return min(max(i, 1), self.n - 1)

    def indices(self, values: np.ndarray) -> np.ndarray:
        """Vectorized :meth:`index` (the observe_batch hot path)."""
        v = np.asarray(values, np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            i = np.floor(np.log(np.maximum(v, 1e-300)) * self._scale
                         - self.lo_exp * self.per_decade).astype(np.int64) + 1
        i[~(v > 10.0 ** self.lo_exp)] = 0
        return np.clip(i, 0, self.n - 1)

    def value(self, i: int) -> float:
        """Representative value for a bin (geometric midpoint; edge
        values for the under/overflow bins)."""
        if i <= 0:
            return 10.0 ** self.lo_exp
        if i >= self.n - 1:
            return 10.0 ** self.hi_exp
        lo = 10.0 ** (self.lo_exp + (i - 1) / self.per_decade)
        hi = 10.0 ** (self.lo_exp + i / self.per_decade)
        return math.sqrt(lo * hi)


# one shared ladder: SLO windows and registry histograms agree on bins
LOG_BINS = LogBins()


def quantile_from_counts(counts: np.ndarray, q: float,
                         bins: LogBins = LOG_BINS) -> Optional[float]:
    """Quantile estimate (seconds/native units) from a bin-count vector;
    None when the sketch is empty."""
    total = int(counts.sum())
    if total == 0:
        return None
    target = q * total
    cum = 0
    for i, c in enumerate(counts):
        cum += int(c)
        if cum >= target:
            return bins.value(i)
    return bins.value(len(counts) - 1)


class SLOTracker:
    """Sliding-window SLO compliance for one serving surface; see module
    docs.  ``window_s`` x ``n_windows`` is the long alert horizon
    (default 10 s x 30 = 5 min); the short horizon is the current
    window.  Thread-safe; the clock is injectable for tests."""

    def __init__(self, p99_ms: float, availability: float = DEFAULT_AVAILABILITY,
                 window_s: float = 10.0, n_windows: int = 30,
                 clock: Callable[[], float] = time.monotonic,
                 bins: LogBins = LOG_BINS):
        self.p99_ms = float(p99_ms)
        self.availability_objective = min(max(float(availability), 0.0),
                                          1.0 - 1e-9)
        self.window_s = float(window_s)
        self.n_windows = int(n_windows)
        self.clock = clock
        self.bins = bins
        self._lock = threading.Lock()
        self._t0 = clock()
        # ring of windows: slot s holds absolute window number _win_no[s]
        self._counts = np.zeros((self.n_windows, bins.n), np.int64)
        self._ok = np.zeros(self.n_windows, np.int64)
        self._err = np.zeros(self.n_windows, np.int64)
        self._over = np.zeros(self.n_windows, np.int64)
        self._win_no = np.full(self.n_windows, -1, np.int64)
        # load-shed accounting (overload protection): sheds are counted
        # SEPARATELY from errors — a coded fast-fail is the designed
        # response to overload, and folding it into availability burn
        # would drain every replica exactly when the fleet most needs
        # them serving (congestion collapse by alerting)
        self._shed = 0

    # ------------------------------------------------------------ writes
    def _slot(self, now: float) -> int:
        """Ring slot for ``now``, resetting it if it held an expired
        window.  Caller holds the lock."""
        wno = int((now - self._t0) / self.window_s)
        s = wno % self.n_windows
        if self._win_no[s] != wno:
            self._counts[s, :] = 0
            self._ok[s] = self._err[s] = self._over[s] = 0
            self._win_no[s] = wno
        return s

    def observe_batch(self, latencies_s: np.ndarray,
                      now: Optional[float] = None) -> None:
        """Fold one batch's per-row latencies (seconds) into the current
        window — one vectorized bincount, no per-request storage."""
        lat = np.asarray(latencies_s, np.float64)
        if lat.size == 0:
            return
        idx = self.bins.indices(lat)
        over = int((lat * 1000.0 > self.p99_ms).sum())
        now = self.clock() if now is None else now
        with self._lock:
            s = self._slot(now)
            self._counts[s] += np.bincount(idx, minlength=self.bins.n)
            self._ok[s] += lat.size
            self._over[s] += over

    def record_errors(self, n: int = 1, now: Optional[float] = None) -> None:
        now = self.clock() if now is None else now
        with self._lock:
            self._err[self._slot(now)] += int(n)

    def record_shed(self, n: int = 1) -> None:
        """Count load-shed requests (admission rejects, deadline drops,
        client cancels) — deliberately OUTSIDE the availability budget;
        see the constructor comment."""
        with self._lock:
            self._shed += int(n)

    @property
    def shed_total(self) -> int:
        return self._shed

    # ------------------------------------------------------------- reads
    def _merged(self, horizon_s: Optional[float],
                now: float) -> Tuple[np.ndarray, int, int, int]:
        """(bin counts, ok, err, over) summed over the windows inside
        ``horizon_s`` (None = the whole ring), current partial window
        included."""
        cur = int((now - self._t0) / self.window_s)
        if horizon_s is None:
            need = self.n_windows
        else:
            need = max(1, int(math.ceil(horizon_s / self.window_s)))
        with self._lock:
            live = (self._win_no > cur - need) & (self._win_no >= 0) \
                & (self._win_no <= cur)
            return (self._counts[live].sum(axis=0),
                    int(self._ok[live].sum()), int(self._err[live].sum()),
                    int(self._over[live].sum()))

    def quantile_ms(self, q: float, horizon_s: Optional[float] = None,
                    now: Optional[float] = None) -> Optional[float]:
        now = self.clock() if now is None else now
        counts, _, _, _ = self._merged(horizon_s, now)
        v = quantile_from_counts(counts, q, self.bins)
        return None if v is None else v * 1000.0

    def availability_observed(self, horizon_s: Optional[float] = None,
                              now: Optional[float] = None) -> float:
        now = self.clock() if now is None else now
        _, ok, err, _ = self._merged(horizon_s, now)
        total = ok + err
        return 1.0 if total == 0 else ok / total

    def burn_rates(self, horizon_s: Optional[float] = None,
                   now: Optional[float] = None) -> Dict[str, float]:
        """{'latency': burn, 'availability': burn} over the horizon —
        observed failure fraction over the budgeted allowance."""
        now = self.clock() if now is None else now
        _, ok, err, over = self._merged(horizon_s, now)
        total = ok + err
        out = {"latency": 0.0, "availability": 0.0}
        if ok:
            out["latency"] = (over / ok) / LATENCY_BUDGET_FRAC
        if total:
            allowed = max(1.0 - self.availability_objective, 1e-9)
            out["availability"] = (err / total) / allowed
        return {k: round(v, 3) for k, v in out.items()}

    def alerts(self, now: Optional[float] = None) -> List[Dict[str, Any]]:
        """Multi-window burn-rate alerts (see module docs): a rule fires
        when the burn exceeds its threshold over BOTH the short horizon
        (current window) and the long horizon (the ring)."""
        now = self.clock() if now is None else now
        short = self.burn_rates(self.window_s, now=now)
        long_ = self.burn_rates(None, now=now)
        out: List[Dict[str, Any]] = []
        for budget in ("latency", "availability"):
            for severity, threshold in ALERT_RULES:
                if short[budget] >= threshold and long_[budget] >= threshold:
                    out.append({"severity": severity, "budget": budget,
                                "burn_short": short[budget],
                                "burn_long": long_[budget],
                                "threshold": threshold})
                    break
        return out

    # ---------------------------------------------------------- surfaces
    def summary(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The ``/slo`` payload: objectives, short/long horizon numbers,
        burn rates and any firing alerts."""
        now = self.clock() if now is None else now
        doc: Dict[str, Any] = {
            "objectives": {"p99_ms": self.p99_ms,
                           "availability": self.availability_objective},
            "window_s": self.window_s,
            "horizon_s": self.window_s * self.n_windows,
            "horizons": {},
        }
        for label, horizon in (("short", self.window_s), ("long", None)):
            _, ok, err, over = self._merged(horizon, now)
            doc["horizons"][label] = {
                "requests": ok + err,
                "errors": err,
                "over_objective": over,
                "p50_ms": self.quantile_ms(0.50, horizon, now=now),
                "p99_ms": self.quantile_ms(0.99, horizon, now=now),
                "availability": round(
                    self.availability_observed(horizon, now=now), 6),
                "burn": self.burn_rates(horizon, now=now),
            }
        doc["alerts"] = self.alerts(now=now)
        doc["alerting"] = bool(doc["alerts"])
        doc["shed"] = self._shed
        return doc

    def compact(self, now: Optional[float] = None) -> Dict[str, Any]:
        """The heartbeat-sized summary ``shifu-tpu monitor`` renders."""
        now = self.clock() if now is None else now
        burn_s = self.burn_rates(self.window_s, now=now)
        burn_l = self.burn_rates(None, now=now)
        alerts = self.alerts(now=now)
        return {
            "p99_ms": self.quantile_ms(0.99, now=now),
            "objective_p99_ms": self.p99_ms,
            "availability": round(self.availability_observed(now=now), 6),
            "objective_availability": self.availability_objective,
            "burn_short": max(burn_s.values()) if burn_s else 0.0,
            "burn_long": max(burn_l.values()) if burn_l else 0.0,
            "alerting": bool(alerts),
            "alerts": [f"{a['severity']}:{a['budget']}" for a in alerts],
        }

    def emit_gauges(self, now: Optional[float] = None) -> None:
        """Mirror the headline numbers into ``slo.*`` registry gauges
        (no-op when telemetry is disabled) — the metrics.prom surface."""
        from . import registry
        now = self.clock() if now is None else now
        p50 = self.quantile_ms(0.50, now=now)
        p99 = self.quantile_ms(0.99, now=now)
        if p50 is not None:
            registry.gauge("slo.p50_ms").set(p50)
        if p99 is not None:
            registry.gauge("slo.p99_ms").set(p99)
        registry.gauge("slo.availability").set(
            self.availability_observed(now=now))
        burn_s = self.burn_rates(self.window_s, now=now)
        burn_l = self.burn_rates(None, now=now)
        registry.gauge("slo.burn_rate_short").set(max(burn_s.values()))
        registry.gauge("slo.burn_rate_long").set(max(burn_l.values()))
        registry.gauge("slo.alerts_firing").set(len(self.alerts(now=now)))


# hysteresis: consecutive stressed evaluations before brownout engages,
# consecutive healthy ones before it lifts.  Exit is slower than entry
# on purpose — flapping in and out of degraded mode is worse than
# staying degraded one beat too long.
BROWNOUT_ENTER_CHECKS = 2
BROWNOUT_EXIT_CHECKS = 3

NORMAL, BROWNOUT = "normal", "brownout"


class BrownoutGovernor:
    """Hysteresis state machine behind the serving brownout mode.

    The worker evaluates one boolean per heartbeat — *stressed* =
    sustained burn-rate alert OR queue buildup — and feeds it to
    :meth:`check`; the governor debounces it into a ``normal`` <->
    ``brownout`` mode with asymmetric hysteresis (enter after
    ``enter_checks`` consecutive stressed beats, exit after
    ``exit_checks`` consecutive healthy ones).  The POLICY of what
    brownout suspends lives in the server (shrink the flush deadline,
    stop trace/score-log sampling and ladder refinement); this class
    only decides WHEN."""

    def __init__(self, enter_checks: int = BROWNOUT_ENTER_CHECKS,
                 exit_checks: int = BROWNOUT_EXIT_CHECKS):
        self.enter_checks = max(1, int(enter_checks))
        self.exit_checks = max(1, int(exit_checks))
        self.mode = NORMAL
        self.entries = 0                 # lifetime brownout entries
        self._stressed_run = 0
        self._healthy_run = 0

    def check(self, stressed: bool) -> bool:
        """Fold one evaluation in; True when the MODE just changed."""
        if stressed:
            self._stressed_run += 1
            self._healthy_run = 0
        else:
            self._healthy_run += 1
            self._stressed_run = 0
        if self.mode == NORMAL \
                and self._stressed_run >= self.enter_checks:
            self.mode = BROWNOUT
            self.entries += 1
            return True
        if self.mode == BROWNOUT \
                and self._healthy_run >= self.exit_checks:
            self.mode = NORMAL
            return True
        return False
