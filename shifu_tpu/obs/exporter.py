"""Metrics snapshots — periodic OpenMetrics-text + JSON registry dumps.

One export format for every consumer: an external scraper (Prometheus
file-sd / node-exporter textfile collector) reads
``<modelset>/telemetry/metrics.prom``, anything programmatic (our bench,
the monitor, dashboards) reads the sibling ``metrics.json``; both are
rendered from the SAME registry snapshot so they can never disagree.

Naming is schema-versioned: every metric name is prefixed
``shifu_tpu_`` and sanitized to the OpenMetrics charset (dots become
underscores: ``ingest.bytes_read`` -> ``shifu_tpu_ingest_bytes_read``),
counters get the conventional ``_total`` suffix, and every exposition
carries ``shifu_tpu_telemetry_schema_version`` so a scraper can detect a
layout change instead of silently mis-joining series (the same contract
as the bench/obs schema handshake).

Histograms export as summaries: ``_count`` + ``_sum`` (counters),
``{quantile="0.5"}`` / ``{quantile="0.99"}`` sample lines (the registry
histogram's fixed-bin log sketch, schema v8 — the OpenMetrics summary
convention, so a scraper gets p50/p99 without buckets) and ``_min`` /
``_max`` / ``_last`` gauges (see
:class:`shifu_tpu.obs.registry.Histogram`).

:class:`MetricsExporter` is the periodic writer: a daemon thread dumping
both files through :mod:`ioutil` atomic writes every ``interval_s`` (the
heartbeat cadence by default), plus a final dump at ``stop()`` so the
last scrape of a finished step sees its closing totals.  Zero-cost when
telemetry is disabled: :func:`start_exporter` returns ``None``.
"""

from __future__ import annotations

import json
import logging
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from ..ioutil import atomic_write_json, atomic_write_text
from . import registry, tracer

log = logging.getLogger(__name__)

METRICS_PROM_BASENAME = "metrics.prom"
METRICS_JSON_BASENAME = "metrics.json"
NAME_PREFIX = "shifu_tpu_"

_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")


def metric_name(name: str) -> str:
    """Registry name -> OpenMetrics name: prefix + charset sanitize."""
    n = _SANITIZE.sub("_", name)
    if not n or not (n[0].isalpha() or n[0] == "_"):
        n = "_" + n
    return NAME_PREFIX + n


def _fmt(v: Any) -> str:
    if v is None:
        return "NaN"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def render_openmetrics(records: Optional[List[Dict[str, Any]]] = None
                       ) -> str:
    """The OpenMetrics text exposition for a registry snapshot (the
    current registry when ``records`` is None)."""
    if records is None:
        records = registry.snapshot(reset=False)
    lines: List[str] = []
    ver = metric_name("telemetry.schema_version")
    lines += [f"# TYPE {ver} gauge",
              f"{ver} {tracer.SCHEMA_VERSION}"]
    for rec in records:
        name = metric_name(rec["name"])
        kind = rec.get("type")
        if kind == "counter":
            lines += [f"# TYPE {name} counter",
                      f"{name}_total {_fmt(rec.get('value'))}"]
        elif kind == "gauge":
            lines += [f"# TYPE {name} gauge",
                      f"{name} {_fmt(rec.get('value'))}"]
        elif kind == "histogram":
            lines += [f"# TYPE {name} summary",
                      f"{name}_count {_fmt(rec.get('count'))}",
                      f"{name}_sum {_fmt(rec.get('sum'))}"]
            # quantile sample lines (summary convention): p50/p99 from
            # the registry histogram's log sketch; pre-v8 records carry
            # no quantiles and render the plain summary as before
            for q, key in (("0.5", "p50"), ("0.99", "p99")):
                if rec.get(key) is not None:
                    lines.append(
                        f'{name}{{quantile="{q}"}} {_fmt(rec.get(key))}')
            for stat in ("min", "max", "last"):
                sname = f"{name}_{stat}"
                lines += [f"# TYPE {sname} gauge",
                          f"{sname} {_fmt(rec.get(stat))}"]
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def snapshot_document(step: Optional[str] = None) -> Dict[str, Any]:
    """The JSON-flavoured snapshot (same registry read as the text
    exposition)."""
    return {
        "kind": "metrics_snapshot",
        "schema_version": tracer.SCHEMA_VERSION,
        "step": step,
        "ts": round(time.time(), 3),
        "pid": os.getpid(),
        "metrics": registry.snapshot(reset=False),
    }


def write_metrics_files(telemetry_dir: str,
                        step: Optional[str] = None) -> None:
    """One synchronized dump of both formats (atomic, crash-safe)."""
    os.makedirs(telemetry_dir, exist_ok=True)
    doc = snapshot_document(step=step)
    atomic_write_json(os.path.join(telemetry_dir, METRICS_JSON_BASENAME),
                      doc, indent=1)
    atomic_write_text(os.path.join(telemetry_dir, METRICS_PROM_BASENAME),
                      render_openmetrics(doc["metrics"]))


class MetricsExporter:
    """Periodic background dump of the registry; see module docs."""

    def __init__(self, telemetry_dir: str, step: Optional[str] = None,
                 interval_s: Optional[float] = None):
        from .health import heartbeat_interval_s
        self.telemetry_dir = telemetry_dir
        self.step = step
        self.interval_s = heartbeat_interval_s(interval_s)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "MetricsExporter":
        self._write()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="shifu-metrics-exporter")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            self._write()

    def _write(self) -> None:
        try:
            write_metrics_files(self.telemetry_dir, step=self.step)
        except Exception:                   # telemetry must never fail a step
            log.debug("metrics export failed", exc_info=True)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, 2 * self.interval_s))
            self._thread = None
        self._write()                        # closing totals for scrapers


def start_exporter(telemetry_dir: str, step: Optional[str] = None,
                   interval_s: Optional[float] = None
                   ) -> Optional[MetricsExporter]:
    """Start the periodic exporter — ``None`` (no thread, no files) when
    telemetry is disabled."""
    if not tracer.enabled():
        return None
    return MetricsExporter(telemetry_dir, step=step,
                           interval_s=interval_s).start()
