"""Metrics registry — named counters/gauges/histograms, host-side only.

The role the reference's Hadoop counters played (rows processed, records
filtered, per-job timings aggregated by the JobTracker): one process-wide
registry every plane reports into, snapshotted into the telemetry JSONL
at each step flush.

Conventions:

- metrics are recorded HOST-SIDE only: instruments coerce through
  ``float()``, so passing a jax tracer (recording from inside ``jit`` /
  ``pjit``) raises — fetch the value first (``float(loss)``), which is
  what every call site does anyway after its value-forcing sync;
- instruments are created on first use and aggregate for the life of the
  step (the step flush resets them);
- when telemetry is disabled every factory returns a shared no-op
  instrument — zero allocation, zero lock traffic.

Device accounting helpers:

- :func:`sample_device_memory` — HBM in-use/peak via
  ``jax.local_devices()[0].memory_stats()`` (absent on some backends;
  silently skipped);
- :func:`ensure_compile_listener` — XLA compile count/time via
  ``jax.monitoring`` duration events (keys containing ``compile``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

import numpy as np

from . import tracer
from .slo import LOG_BINS, quantile_from_counts


# Instruments are THREAD-SAFE: ``ingest.*`` counters increment from the
# ``prepared()`` background prep thread while trainers update ``train.*``
# on the main thread, and the heartbeat/exporter threads (obs/health,
# obs/exporter) snapshot the same instruments concurrently.  A bare
# ``self.value += n`` is a read-modify-write the GIL does NOT make atomic
# (the interpreter can switch between the load and the store), so every
# mutation and every read-out takes the instrument's own lock.
class Counter:
    """Monotonic accumulator (rows processed, epochs, trees built)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        n = float(n)
        with self._lock:
            self.value += n

    def to_record(self) -> Dict[str, Any]:
        with self._lock:
            return {"kind": "metric", "type": "counter", "name": self.name,
                    "value": self.value}


class Gauge:
    """Last-value instrument with a high-water option (loss, throughput,
    device-memory peak)."""

    __slots__ = ("name", "value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self.value: Optional[float] = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.value = v

    def set_max(self, v: float) -> None:
        v = float(v)
        with self._lock:
            if self.value is None or v > self.value:
                self.value = v

    def to_record(self) -> Dict[str, Any]:
        with self._lock:
            return {"kind": "metric", "type": "gauge", "name": self.name,
                    "value": self.value}


class Histogram:
    """Streaming summary (count/sum/min/max/last) plus a fixed-bin LOG
    sketch (:data:`shifu_tpu.obs.slo.LOG_BINS`) so snapshots carry
    p50/p99 estimates (schema v8) — still no per-observation storage."""

    __slots__ = ("name", "count", "sum", "min", "max", "last", "_bins",
                 "_lock")

    def __init__(self, name: str):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.last: Optional[float] = None
        self._bins = np.zeros(LOG_BINS.n, np.int64)
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = LOG_BINS.index(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None or v < self.min else self.min
            self.max = v if self.max is None or v > self.max else self.max
            self.last = v
            self._bins[i] += 1

    @property
    def mean(self) -> float:
        with self._lock:
            return self.sum / self.count if self.count else 0.0

    def quantile(self, q: float) -> Optional[float]:
        """Sketch-resolution quantile (~6.6% relative error per bin)."""
        with self._lock:
            return quantile_from_counts(self._bins, q, LOG_BINS)

    def _q(self, q: float) -> Optional[float]:
        v = quantile_from_counts(self._bins, q, LOG_BINS)
        return None if v is None else round(v, 9)

    def to_record(self) -> Dict[str, Any]:
        with self._lock:
            return {"kind": "metric", "type": "histogram", "name": self.name,
                    "count": self.count, "sum": round(self.sum, 6),
                    "min": self.min, "max": self.max, "last": self.last,
                    "p50": self._q(0.50), "p99": self._q(0.99)}


class _NullInstrument:
    """Shared no-op standing in for every instrument when disabled."""

    __slots__ = ()

    def inc(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def set_max(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


_NULL = _NullInstrument()


class MetricsRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instruments: Dict[str, Any] = {}

    def _get(self, name: str, cls):
        with self._lock:
            inst = self._instruments.get(name)
            if inst is None:
                inst = self._instruments[name] = cls(name)
            elif not isinstance(inst, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{type(inst).__name__}")
            return inst

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self, reset: bool = False) -> List[Dict[str, Any]]:
        with self._lock:
            recs = [inst.to_record()
                    for _, inst in sorted(self._instruments.items())]
            if reset:
                self._instruments.clear()
            return recs

    def reset(self) -> None:
        with self._lock:
            self._instruments.clear()


_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _registry


def counter(name: str):
    return _registry.counter(name) if tracer.enabled() else _NULL


def gauge(name: str):
    return _registry.gauge(name) if tracer.enabled() else _NULL


def histogram(name: str):
    return _registry.histogram(name) if tracer.enabled() else _NULL


def snapshot(reset: bool = False) -> List[Dict[str, Any]]:
    return _registry.snapshot(reset=reset)


# -------------------------------------------------------- device helpers
def sample_device_memory() -> None:
    """Record HBM in-use/peak gauges for local device 0 (the per-step
    high-water mark the YARN container memory counters used to show).
    Backends without ``memory_stats`` (CPU) are silently skipped."""
    if not tracer.enabled():
        return
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return
    if not stats:
        return
    for key, metric in (("bytes_in_use", "device.bytes_in_use"),
                        ("peak_bytes_in_use", "device.peak_bytes_in_use"),
                        ("bytes_limit", "device.bytes_limit")):
        if key in stats:
            # registry-internal writer: three fixed keys per
            # heartbeat sample, not a hot loop
            _registry.gauge(metric).set_max(stats[key])  # shifu-lint: disable=telemetry-guard


_compile_listener_installed = False


def ensure_compile_listener() -> None:
    """Install (once per process) a ``jax.monitoring`` duration listener
    that accumulates XLA compile count/time into ``xla.compile_count`` /
    ``xla.compile_time_s``.  The listener itself checks ``enabled()`` so
    a later disable costs one branch per compile, nothing more."""
    global _compile_listener_installed
    if _compile_listener_installed:
        return
    try:
        try:
            from jax.monitoring import \
                register_event_duration_secs_listener as _register
        except ImportError:
            from jax._src.monitoring import \
                register_event_duration_secs_listener as _register
    except Exception:
        return

    def _listener(name: str, secs: float, **kw) -> None:
        if "compile" in name and tracer.enabled():
            _registry.counter("xla.compile_count").inc()
            _registry.counter("xla.compile_time_s").inc(secs)

    try:
        _register(_listener)
        _compile_listener_installed = True
    except Exception:
        pass
