"""Report surface — render a telemetry trace as a per-step span tree.

``shifu-tpu analysis --telemetry`` reads ``<modelset>/telemetry/
trace.jsonl`` (blocks appended by each step's flush, see
:mod:`shifu_tpu.obs.tracer` for the schema) and prints, per step: the
span tree with total and SELF time (total minus direct children — where
the step actually spent its wall-clock), rows/sec where a span carries a
``rows`` attribute, summarized per-epoch/tree events, and the metric
snapshot.  The closing line aggregates the whole pipeline.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List

TRACE_BASENAME = "trace.jsonl"


def trace_path(model_set_dir: str) -> str:
    return os.path.join(os.path.abspath(model_set_dir), "telemetry",
                        TRACE_BASENAME)


def load_blocks(path: str) -> List[Dict[str, Any]]:
    """Parse the JSONL into flush blocks: ``{"meta", "spans", "events",
    "metrics"}`` per block, skipping unparseable lines (a crashed run may
    truncate the tail)."""
    blocks: List[Dict[str, Any]] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            kind = rec.get("kind")
            if kind == "meta":
                blocks.append({"meta": rec, "spans": [], "events": [],
                               "metrics": []})
                continue
            if not blocks:       # tolerate a headerless fragment
                blocks.append({"meta": {"step": None, "ts": None},
                               "spans": [], "events": [], "metrics": []})
            if kind == "span":
                blocks[-1]["spans"].append(rec)
            elif kind == "event":
                blocks[-1]["events"].append(rec)
            elif kind == "metric":
                blocks[-1]["metrics"].append(rec)
    return blocks


def _fmt_attrs(attrs: Dict[str, Any], dur: float) -> str:
    parts = []
    rows = attrs.get("rows")
    if isinstance(rows, (int, float)) and dur > 0:
        parts.append(f"{rows:,.0f} rows ({rows / dur:,.0f} rows/s)")
    for k, v in attrs.items():
        if k in ("rows", "kind"):
            continue
        parts.append(f"{k}={v}")
    return ("  " + " ".join(parts)) if parts else ""


def _render_block(block: Dict[str, Any], out: List[str]) -> float:
    meta = block["meta"]
    spans = block["spans"]
    by_id = {s["id"]: s for s in spans}
    children: Dict[Any, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        parent = s.get("parent")
        if parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    ev_by_parent: Dict[Any, List[dict]] = {}
    for e in block["events"]:
        ev_by_parent.setdefault(e.get("parent"), []).append(e)

    total = sum(s["dur_s"] for s in roots)
    ts = meta.get("ts")
    when = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts)) \
        if ts else "?"
    out.append(f"== {meta.get('step') or '(unlabeled)'}  {when}  "
               f"total {total:.3f}s")

    def _events_line(span_id: Any, indent: str) -> None:
        evs = ev_by_parent.pop(span_id, None)
        if not evs:
            return
        by_name: Dict[str, List[dict]] = {}
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)
        for name, group in by_name.items():
            last = group[-1]["attrs"]
            tail = " ".join(f"{k}={_num(v)}" for k, v in last.items())
            out.append(f"{indent}· {name} ×{len(group)}"
                       + (f"  (last: {tail})" if tail else ""))

    def _walk(s: dict, depth: int) -> None:
        kids = sorted(children.get(s["id"], []), key=lambda c: c["ts"])
        self_s = s["dur_s"] - sum(k["dur_s"] for k in kids)
        indent = "  " * depth
        label = f"{indent}{s['name']}"
        out.append(f"{label:<38}{s['dur_s']:>10.3f}s  self "
                   f"{max(self_s, 0.0):>8.3f}s"
                   f"{_fmt_attrs(s.get('attrs') or {}, s['dur_s'])}")
        _events_line(s["id"], indent + "  ")
        for k in kids:
            _walk(k, depth + 1)

    for r in sorted(roots, key=lambda s: s["ts"]):
        _walk(r, 1)
    _events_line(None, "  ")          # events outside any span
    for m in block["metrics"]:
        if m["type"] == "histogram":
            mean = m["sum"] / m["count"] if m.get("count") else 0.0
            out.append(f"  metric {m['name']}: count={m['count']} "
                       f"mean={mean:.4g} min={_num(m['min'])} "
                       f"max={_num(m['max'])}")
        else:
            out.append(f"  metric {m['name']}: {_num(m.get('value'))} "
                       f"({m['type']})")
    # ingest stall: fraction of the step's wall-clock the consumer spent
    # blocked waiting for windows/H2D (the accelerator-starvation signal
    # the out-of-core overhaul exists to drive toward zero)
    wait = next((m.get("value") for m in block["metrics"]
                 if m.get("name") == "ingest.h2d_wait_seconds"), None)
    if wait is not None and total > 0:
        frac = min(float(wait) / total, 1.0)
        out.append(f"  ingest stall fraction: {frac:.1%} "
                   f"({float(wait):.3f}s blocked on ingest of "
                   f"{total:.3f}s wall)")
    # disk-tail plane: how often the out-of-core remainder re-streamed
    # (the super-batch schedule's cost driver — passes, not rows, are
    # what the one-pass-feeds-everything restructure bounds)
    mvals = {m.get("name"): m.get("value") for m in block["metrics"]}
    sweeps = mvals.get("train.tail_sweeps")
    if sweeps:
        passes = mvals.get("ingest.disk_passes") or 0
        repairs = mvals.get("train.tail_repairs") or 0
        rlevels = mvals.get("train.tail_repair_levels") or 0
        line = (f"  tail sweeps: {int(sweeps)} "
                f"({int(passes)} disk passes total")
        if repairs:
            line += (f", {int(repairs)} speculation repairs over "
                     f"{int(rlevels)} levels")
        out.append(line + ")")
    return total


def _num(v: Any) -> Any:
    if isinstance(v, float):
        return f"{v:.6g}"
    return v


def render_telemetry(model_set_dir: str) -> str:
    """The ``analysis --telemetry`` payload for a model-set dir."""
    path = trace_path(model_set_dir)
    if not os.path.isfile(path):
        return (f"no telemetry trace at {path}\n"
                "run steps with SHIFU_TPU_TELEMETRY=1 (or --telemetry / "
                "-Dshifu.telemetry=on) first")
    blocks = load_blocks(path)
    if not blocks:
        return f"telemetry trace {path} is empty"
    out: List[str] = [f"telemetry: {path}",
                      f"schema v{blocks[-1]['meta'].get('schema_version')}"
                      f", {len(blocks)} step record(s)", ""]
    grand = 0.0
    for block in blocks:
        grand += _render_block(block, out)
        out.append("")
    out.append(f"pipeline total: {grand:.3f}s across {len(blocks)} "
               "step record(s)")
    return "\n".join(out)
