"""Report surface — render a telemetry trace as a per-step span tree.

``shifu-tpu analysis --telemetry`` reads ``<modelset>/telemetry/
trace.jsonl`` (blocks appended by each step's flush, see
:mod:`shifu_tpu.obs.tracer` for the schema) and prints, per step: the
span tree with total and SELF time (total minus direct children — where
the step actually spent its wall-clock), rows/sec where a span carries a
``rows`` attribute, summarized per-epoch/tree events, and the metric
snapshot.  The closing line aggregates the whole pipeline.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

log = logging.getLogger(__name__)

TRACE_BASENAME = "trace.jsonl"

NO_TELEMETRY_HINT = ("no telemetry recorded (enable with "
                     "SHIFU_TPU_TELEMETRY=1, --telemetry, or "
                     "-Dshifu.telemetry=on)")


def trace_path(model_set_dir: str) -> str:
    return os.path.join(os.path.abspath(model_set_dir), "telemetry",
                        TRACE_BASENAME)


def load_blocks(path: str,
                skipped: Optional[List[str]] = None) -> List[Dict[str, Any]]:
    """Parse the JSONL into flush blocks: ``{"meta", "spans", "events",
    "metrics", "costs"}`` per block.  Unparseable lines — a crash
    mid-write tears the final line — are SKIPPED with a warning (and
    appended to ``skipped`` when given), never a parse failure: a
    crashed run's partial trace is exactly the one you want to read."""
    blocks: List[Dict[str, Any]] = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                log.warning("telemetry trace %s line %d is not valid JSON "
                            "(torn write from a crashed run?) — skipping",
                            path, lineno)
                if skipped is not None:
                    skipped.append(f"line {lineno}")
                continue
            kind = rec.get("kind")
            if kind == "meta":
                blocks.append({"meta": rec, "spans": [], "events": [],
                               "metrics": [], "costs": []})
                continue
            if not blocks:       # tolerate a headerless fragment
                blocks.append({"meta": {"step": None, "ts": None},
                               "spans": [], "events": [], "metrics": [],
                               "costs": []})
            if kind == "span":
                blocks[-1]["spans"].append(rec)
            elif kind == "event":
                blocks[-1]["events"].append(rec)
            elif kind == "metric":
                blocks[-1]["metrics"].append(rec)
            elif kind == "cost":
                blocks[-1]["costs"].append(rec)
    return blocks


def _fmt_attrs(attrs: Dict[str, Any], dur: float) -> str:
    parts = []
    rows = attrs.get("rows")
    if isinstance(rows, (int, float)) and dur > 0:
        parts.append(f"{rows:,.0f} rows ({rows / dur:,.0f} rows/s)")
    for k, v in attrs.items():
        if k in ("rows", "kind"):
            continue
        parts.append(f"{k}={v}")
    return ("  " + " ".join(parts)) if parts else ""


def _render_block(block: Dict[str, Any], out: List[str]) -> float:
    meta = block["meta"]
    spans = block["spans"]
    by_id = {s["id"]: s for s in spans}
    children: Dict[Any, List[dict]] = {}
    roots: List[dict] = []
    for s in spans:
        parent = s.get("parent")
        if parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    ev_by_parent: Dict[Any, List[dict]] = {}
    for e in block["events"]:
        ev_by_parent.setdefault(e.get("parent"), []).append(e)

    # wall-clock total counts MAIN-THREAD roots only: ingest-thread spans
    # (the prep pipeline) run CONCURRENTLY with the step and would
    # double-count the overlap the pipelining exists to create
    main_roots = [s for s in roots
                  if s.get("tid") in (None, "MainThread")]
    total = sum(s["dur_s"] for s in (main_roots or roots))
    ts = meta.get("ts")
    when = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(ts)) \
        if ts else "?"
    out.append(f"== {meta.get('step') or '(unlabeled)'}  {when}  "
               f"total {total:.3f}s")

    def _events_line(span_id: Any, indent: str) -> None:
        evs = ev_by_parent.pop(span_id, None)
        if not evs:
            return
        by_name: Dict[str, List[dict]] = {}
        for e in evs:
            by_name.setdefault(e["name"], []).append(e)
        for name, group in by_name.items():
            last = group[-1]["attrs"]
            tail = " ".join(f"{k}={_num(v)}" for k, v in last.items())
            out.append(f"{indent}· {name} ×{len(group)}"
                       + (f"  (last: {tail})" if tail else ""))

    def _walk(s: dict, depth: int) -> None:
        kids = sorted(children.get(s["id"], []), key=lambda c: c["ts"])
        self_s = s["dur_s"] - sum(k["dur_s"] for k in kids)
        indent = "  " * depth
        label = f"{indent}{s['name']}"
        out.append(f"{label:<38}{s['dur_s']:>10.3f}s  self "
                   f"{max(self_s, 0.0):>8.3f}s"
                   f"{_fmt_attrs(s.get('attrs') or {}, s['dur_s'])}")
        _events_line(s["id"], indent + "  ")
        for group in _grouped(kids):
            if len(group) == 1:
                _walk(group[0], depth + 1)
            else:
                _agg_line(group, depth + 1)

    def _agg_line(group: List[dict], depth: int) -> None:
        """Repeated same-name siblings (per-window ingest spans, per-tree
        spans) collapse to one aggregate line — a 500-window sweep is one
        line with a count, not 500."""
        indent = "  " * depth
        dur = sum(g["dur_s"] for g in group)
        rows = sum(g.get("attrs", {}).get("rows") or 0 for g in group)
        label = f"{indent}{group[0]['name']} ×{len(group)}"
        tail = f"  {rows:,.0f} rows ({rows / dur:,.0f} rows/s)" \
            if rows and dur > 0 else ""
        out.append(f"{label:<38}{dur:>10.3f}s  (aggregated){tail}")

    for group in _grouped(sorted(roots, key=lambda s: s["ts"])):
        if len(group) == 1:
            _walk(group[0], 1)
        else:
            _agg_line(group, 1)
    _events_line(None, "  ")          # events outside any span
    for m in block["metrics"]:
        if m["type"] == "histogram":
            mean = m["sum"] / m["count"] if m.get("count") else 0.0
            out.append(f"  metric {m['name']}: count={m['count']} "
                       f"mean={mean:.4g} min={_num(m['min'])} "
                       f"max={_num(m['max'])}")
        else:
            out.append(f"  metric {m['name']}: {_num(m.get('value'))} "
                       f"({m['type']})")
    # ingest stall: fraction of the step's wall-clock the consumer spent
    # blocked waiting for windows/H2D (the accelerator-starvation signal
    # the out-of-core overhaul exists to drive toward zero)
    wait = next((m.get("value") for m in block["metrics"]
                 if m.get("name") == "ingest.h2d_wait_seconds"), None)
    if wait is not None and total > 0:
        frac = min(float(wait) / total, 1.0)
        out.append(f"  ingest stall fraction: {frac:.1%} "
                   f"({float(wait):.3f}s blocked on ingest of "
                   f"{total:.3f}s wall)")
    # parse stall: fraction of the step's wall-clock the consumer spent
    # blocked on the raw-shard parse pool (0 when the pool keeps ahead
    # of the accumulators or the pass was served from the raw cache)
    mvals = {m.get("name"): m.get("value") for m in block["metrics"]}
    pstall = mvals.get("ingest.parse_stall_frac")
    if pstall is not None:
        out.append(f"  parse stall fraction: {float(pstall):.1%} "
                   "(consumer blocked on the parse pool)")
    hits, misses = (mvals.get("rawcache.hits"),
                    mvals.get("rawcache.misses"))
    if hits or misses:
        mb = (mvals.get("rawcache.bytes_written") or 0) / 1e6
        out.append(f"  raw cache: {int(hits or 0)} pass(es) served "
                   f"decoded, {int(misses or 0)} parsed from text"
                   + (f", {mb:,.1f} MB written" if mb else ""))
    # disk-tail plane: how often the out-of-core remainder re-streamed
    # (the super-batch schedule's cost driver — passes, not rows, are
    # what the one-pass-feeds-everything restructure bounds)
    sweeps = mvals.get("train.tail_sweeps")
    if sweeps:
        passes = mvals.get("ingest.disk_passes") or 0
        repairs = mvals.get("train.tail_repairs") or 0
        rlevels = mvals.get("train.tail_repair_levels") or 0
        line = (f"  tail sweeps: {int(sweeps)} "
                f"({int(passes)} disk passes total")
        if repairs:
            line += (f", {int(repairs)} speculation repairs over "
                     f"{int(rlevels)} levels")
        out.append(line + ")")
    return total


def _num(v: Any) -> Any:
    if isinstance(v, float):
        return f"{v:.6g}"
    return v


# siblings sharing a name above this count render as one aggregate line
AGGREGATE_OVER = 3


def _grouped(spans: List[dict]) -> List[List[dict]]:
    """Partition an ordered sibling list: names occurring more than
    ``AGGREGATE_OVER`` times become one group, everything else stays a
    singleton in original order."""
    by_name: Dict[str, int] = {}
    for s in spans:
        by_name[s["name"]] = by_name.get(s["name"], 0) + 1
    groups: List[List[dict]] = []
    agg: Dict[str, List[dict]] = {}
    for s in spans:
        if by_name[s["name"]] > AGGREGATE_OVER:
            bucket = agg.get(s["name"])
            if bucket is None:
                bucket = agg[s["name"]] = []
                groups.append(bucket)
            bucket.append(s)
        else:
            groups.append([s])
    return groups


def _render_drift(model_set_dir: str, out: List[str]) -> None:
    """The drift section: the live PSI table ``obs/drift`` emitted as
    ``telemetry/drift.json`` (absent = no drift monitor ran)."""
    path = os.path.join(os.path.abspath(model_set_dir), "telemetry",
                        "drift.json")
    if not os.path.isfile(path):
        return
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError):
        out.append(f"drift: {path} unreadable (torn write?)")
        return
    out.append(f"drift: {d.get('rows', 0):,} live rows vs training "
               f"snapshot (threshold {d.get('threshold')})")
    cols = sorted((d.get("columns") or {}).items(),
                  key=lambda kv: -kv[1])
    for name, v in cols[:10]:
        flag = "  << DRIFTING" if v > (d.get("threshold") or 0.25) else ""
        out.append(f"  psi {name}: {v:.4f}{flag}")
    if len(cols) > 10:
        out.append(f"  ... {len(cols) - 10} more column(s) in {path}")
    flagged = d.get("flagged") or []
    out.append(f"  {len(flagged)} column(s) over threshold"
               + (f": {', '.join(flagged)}" if flagged else ""))
    out.append("")


def _q(v: Any) -> str:
    return "-" if v is None else f"{float(v):.4f}"


def _render_quality(model_set_dir: str, out: List[str]) -> None:
    """The model-quality section: the live AUC / calibration / score-PSI
    table ``obs/quality`` emitted as ``telemetry/quality.json`` (absent
    = the score-log plane never ran).  Rendering is byte-deterministic
    for a given artifact: generations sorted newest-first, fixed-width
    floats."""
    path = os.path.join(os.path.abspath(model_set_dir), "telemetry",
                        "quality.json")
    if not os.path.isfile(path):
        return
    try:
        with open(path) as f:
            q = json.load(f)
    except (OSError, json.JSONDecodeError):
        out.append(f"quality: {path} unreadable (torn write?)")
        return
    out.append(f"quality: {int(q.get('joined') or 0):,} joined rows vs "
               f"posttrain baseline auc {_q(q.get('baseline_auc'))} "
               f"(delta threshold {_q(q.get('auc_delta'))}, "
               f"psi threshold {_q(q.get('psi_threshold'))})")
    gens = sorted(((int(g), row) for g, row in
                   (q.get("generations") or {}).items()), reverse=True)
    for g, row in gens:
        out.append(f"  gen {g}: auc={_q(row.get('live_auc'))} "
                   f"ece={_q(row.get('ece'))} "
                   f"psi={_q(row.get('psi'))}  "
                   f"{int(row.get('joined') or 0):,} joined / "
                   f"{int(row.get('scored') or 0):,} scored")
    if q.get("degraded"):
        out.append("  << QUALITY DEGRADED "
                   f"({', '.join(q.get('reasons') or [])})")
    out.append("")


def render_telemetry(model_set_dir: str) -> str:
    """The ``analysis --telemetry`` payload for a model-set dir.  Missing
    or empty traces render a hint, not an error — the CLI exits 0 either
    way (a monitoring query on a fresh model set is not a failure)."""
    path = trace_path(model_set_dir)
    if not os.path.isfile(path):
        return f"{NO_TELEMETRY_HINT}\nexpected trace at {path}"
    skipped: List[str] = []
    blocks = load_blocks(path, skipped=skipped)
    if not blocks:
        return (f"{NO_TELEMETRY_HINT}\ntrace {path} "
                + ("holds no parseable records "
                   f"({len(skipped)} torn line(s) skipped)" if skipped
                   else "is empty"))
    out: List[str] = [f"telemetry: {path}",
                      f"schema v{blocks[-1]['meta'].get('schema_version')}"
                      f", {len(blocks)} step record(s)"]
    if skipped:
        out.append(f"warning: {len(skipped)} torn line(s) skipped "
                   f"({', '.join(skipped[:5])}) — crashed run mid-write")
    out.append("")
    grand = 0.0
    for block in blocks:
        grand += _render_block(block, out)
        out.append("")
    _render_drift(model_set_dir, out)
    _render_quality(model_set_dir, out)
    out.append(f"pipeline total: {grand:.3f}s across {len(blocks)} "
               "step record(s)")
    return "\n".join(out)


def render_telemetry_merged(dirs: List[str]) -> str:
    """``analysis --telemetry --aggregate``: N process telemetry dirs as
    ONE report — each dir's span tree (headed by its clock offset) plus
    the merged per-proc step-lag table from the health plane."""
    from .monitor import (aggregate_records, dir_clock_offset,
                          step_lag_table)
    out: List[str] = [f"merged telemetry over {len(dirs)} dir(s)"]
    for d in dirs:
        off = dir_clock_offset(d)
        out.append("")
        out.append(f"==== {os.path.abspath(d)} "
                   f"(clock offset {off:+.1f}s)")
        out.append(render_telemetry(d))
    recs, _counts = aggregate_records(dirs)
    if recs:
        out.append("")
        out.append("==== per-proc step lag (health plane, "
                   "clock-normalized)")
        for row in step_lag_table(recs):
            lag_s = f"{row['lag_s']:.1f}s" \
                if row["lag_s"] is not None else "-"
            out.append(f"  {row['step']:<11}{(row['proc'] or '?'):<22}"
                       f"{(row['dir'] or '?'):<14}"
                       f"rows {row['rows']:<12,.0f}"
                       f"lag {row['rows_lag']:<10,.0f}{lag_s}")
    return "\n".join(out)
