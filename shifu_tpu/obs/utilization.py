"""Utilization & roofline report — did the wall-clock buy real work?

``shifu-tpu analysis --telemetry --utilization`` joins the cost records
(:mod:`obs.costs`: per-executable FLOPs / bytes accessed × launches)
against the fenced span wall times of each flush block and reports, per
PLANE (the executable-name prefix: ``nn.``, ``gbt.``, ``stats.``, …):

- total FLOPs and bytes moved, achieved FLOP/s and bytes/s over the
  step's main-thread wall-clock;
- percent of the device's peak FLOP/s and peak bandwidth (peak table in
  :mod:`obs.costs`, overridable via ``SHIFU_TPU_PEAK_FLOPS`` /
  ``SHIFU_TPU_PEAK_BW``);
- the roofline verdict: operational intensity (FLOPs/byte) under the
  machine balance point ⇒ *bandwidth-bound*, over ⇒ *compute-bound* —
  which roof the plane is actually pushing against;
- padding waste: padded vs real rows per window bucket
  (``ingest.rows_padded`` / ``ingest.rows_emitted``), the fraction of
  ingest/compute spent on rows that carry zero weight.

Rendering is DETERMINISTIC by construction — stable sorts (step order as
flushed, planes alphabetically) and fixed float formatting — so the
golden test diffs cleanly across runs on the same trace.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from .costs import resolve_peaks
from .report import NO_TELEMETRY_HINT, load_blocks, trace_path


def _block_wall(block: Dict[str, Any]) -> float:
    """Main-thread root wall-clock of one flush block (the same total
    the span-tree report prints — ingest-thread spans overlap it)."""
    spans = block.get("spans") or []
    by_id = {s["id"]: s for s in spans}
    roots = [s for s in spans if s.get("parent") not in by_id]
    main = [s for s in roots if s.get("tid") in (None, "MainThread")]
    return sum(s.get("dur_s") or 0.0 for s in (main or roots))


def plane_of(name: str) -> str:
    """Executable name -> plane: the prefix before the first dot."""
    return str(name).split(".", 1)[0]


def aggregate_block(block: Dict[str, Any]) -> Dict[str, Dict[str, float]]:
    """Per-plane totals for one block: flops, bytes, launches, compiles,
    executables (entries), analytic entry count."""
    planes: Dict[str, Dict[str, float]] = {}
    for c in block.get("costs") or []:
        p = planes.setdefault(plane_of(c.get("name")), {
            "flops": 0.0, "bytes": 0.0, "launches": 0, "compiles": 0,
            "executables": 0, "analytic": 0})
        launches = int(c.get("launches") or 0)
        p["launches"] += launches
        p["compiles"] += int(c.get("compiles") or 0)
        p["executables"] += 1
        if c.get("analytic"):
            p["analytic"] += 1
        if c.get("flops") is not None:
            p["flops"] += float(c["flops"]) * max(launches, 1)
        if c.get("bytes_accessed") is not None:
            p["bytes"] += float(c["bytes_accessed"]) * max(launches, 1)
    return planes


def verdict_for(flops: float, nbytes: float, peak_flops: float,
                peak_bw: float) -> str:
    """Roofline verdict from operational intensity vs machine balance."""
    if flops <= 0 and nbytes <= 0:
        return "no-cost-data"
    if nbytes <= 0:
        return "compute-bound"
    if flops <= 0:
        return "bandwidth-bound"
    balance = peak_flops / max(peak_bw, 1e-30)    # FLOPs/byte at the ridge
    return "compute-bound" if (flops / nbytes) >= balance \
        else "bandwidth-bound"


def _fmt_e(v: Optional[float]) -> str:
    return "-".rjust(9) if v is None else f"{v:9.3e}"


def _fmt_pct(v: Optional[float]) -> str:
    return "-".rjust(7) if v is None else f"{v:6.2%}".rjust(7)


def _padding_line(block: Dict[str, Any], out: List[str]) -> None:
    mvals = {m.get("name"): m.get("value")
             for m in block.get("metrics") or []}
    padded = mvals.get("ingest.rows_padded")
    real = mvals.get("ingest.rows_emitted")
    if not padded:
        return
    total = float(padded) + float(real or 0.0)
    frac = float(padded) / total if total else 0.0
    out.append(f"  padding waste: {padded:,.0f} padded of {total:,.0f} "
               f"window rows ({frac:.2%} of ingest/compute feeds "
               "zero-weight rows)")


def render_utilization(model_set_dir: str) -> str:
    """The ``--utilization`` payload for a model-set dir (missing/empty
    traces render the usual hint; exit stays 0 at the CLI)."""
    path = trace_path(model_set_dir)
    if not os.path.isfile(path):
        return f"{NO_TELEMETRY_HINT}\nexpected trace at {path}"
    skipped: List[str] = []
    blocks = load_blocks(path, skipped=skipped)
    if not blocks:
        return f"{NO_TELEMETRY_HINT}\ntrace {path} holds no records"
    backend = next((b["meta"].get("backend") for b in blocks
                    if b["meta"].get("backend")), None)
    peak_flops, peak_bw, label = resolve_peaks(backend)
    out: List[str] = [f"utilization: {path}"]
    if skipped:
        out.append(f"warning: {len(skipped)} torn line(s) skipped")
    kind = (backend or {}).get("device_kind", "unknown")
    out.append(f"device: {kind}  peaks[{label}]: "
               f"{peak_flops:.3e} FLOP/s, {peak_bw:.3e} B/s  "
               "(override: SHIFU_TPU_PEAK_FLOPS / SHIFU_TPU_PEAK_BW)")
    out.append("")

    grand_flops = grand_bytes = grand_wall = 0.0
    any_costs = False
    for block in blocks:
        planes = aggregate_block(block)
        if not planes:
            continue
        any_costs = True
        wall = _block_wall(block)
        step = block["meta"].get("step") or "(unlabeled)"
        out.append(f"== {step}  wall {wall:.3f}s")
        out.append(f"  {'plane':<10}{'flops':>10}{'bytes':>10}"
                   f"{'flop/s':>10}{'bytes/s':>10}{'%pkflop':>8}"
                   f"{'%pkbw':>8}{'fl/byte':>11}  verdict")
        for plane in sorted(planes):
            p = planes[plane]
            fl, by = p["flops"], p["bytes"]
            fps = fl / wall if wall > 0 else None
            bps = by / wall if wall > 0 else None
            pctf = (fps / peak_flops) if fps is not None else None
            pctb = (bps / peak_bw) if bps is not None else None
            inten = (fl / by) if by > 0 else None
            v = verdict_for(fl, by, peak_flops, peak_bw)
            out.append(f"  {plane:<10}{_fmt_e(fl):>10}{_fmt_e(by):>10}"
                       f"{_fmt_e(fps):>10}{_fmt_e(bps):>10}"
                       f"{_fmt_pct(pctf):>8}{_fmt_pct(pctb):>8}"
                       f"{_fmt_e(inten):>11}  {v}"
                       + ("  [analytic]" if p["analytic"] else ""))
            grand_flops += fl
            grand_bytes += by
        execs = sum(int(p["executables"]) for p in planes.values())
        compiles = sum(int(p["compiles"]) for p in planes.values())
        launches = sum(int(p["launches"]) for p in planes.values())
        mvals = {m.get("name"): m.get("value")
                 for m in block.get("metrics") or []}
        rec = mvals.get("xla.recompiles")
        out.append(f"  executables: {execs} costed, {compiles} compile(s), "
                   f"{launches} launch(es)"
                   + (f", {rec:.0f} RECOMPILE(S) from shape churn"
                      if rec else ""))
        _padding_line(block, out)
        grand_wall += wall
        out.append("")

    if not any_costs:
        out.append("no cost records in this trace — route entry points "
                   "through obs.costs.costed_jit (schema v6) and re-run "
                   "with telemetry enabled")
        return "\n".join(out)
    mfu = grand_flops / (grand_wall * peak_flops) if grand_wall > 0 else 0.0
    out.append(f"pipeline: {_fmt_e(grand_flops).strip()} FLOPs, "
               f"{_fmt_e(grand_bytes).strip()} bytes over "
               f"{grand_wall:.3f}s costed wall — MFU {mfu:.2%}")
    return "\n".join(out)
