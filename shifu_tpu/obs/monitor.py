"""``shifu-tpu monitor`` — tail the health directory, render live status.

Reads the heartbeat files :mod:`obs.health` writers commit under
``<modelset>/telemetry/health/`` and renders one line per process:
step, state (live / stalled / stale / exited), heartbeat age, the phase
each thread is in right now, and the progress counters (rows, windows,
trees, epochs).  SERVE heartbeats additionally carry queue depth, the
compact SLO summary, and (when the score-log plane is on) the compact
model-quality summary — queue buildup, a firing burn-rate alert and a
degraded quality verdict get their own ``<<`` flags.  The summary line
carries the quorum
fraction — ``healthy / total`` — the primitive ROADMAP #3's
straggler/quorum logic reads.

``--aggregate DIR DIR ...`` merges the health directories of N
processes (one telemetry dir per process/host) into ONE report: a
single merged table tagged by source dir, a merged quorum line, and a
per-proc STEP-LAG table — for each step, every proc's progress against
the front-runner (rows behind, seconds since progress), the per-worker
lag signal the DAG-of-sync-SGD model frames for straggler detection.
Cross-host clocks are normalized per dir: the writer's embedded ``ts``
minus the health file's mtime (both stamp the same atomic commit; on a
shared filesystem the mtime comes from the common fileserver clock)
estimates each process's clock offset, and offsets beyond
``CLOCK_OFFSET_MIN_S`` are subtracted from ages/lags.

Stateless by design: every render is a fresh read of the directory, so
the monitor can attach to (and detach from) a running job at any time,
from any process, with no coordination.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import tracer
from .health import classify, health_dir_for, read_health

# `monitor --once --json` exit code when any process is stalled/stale —
# distinct from generic failure (1) and the bench/schema mismatch (2)
EXIT_UNHEALTHY = 3

_STATE_FLAGS = {"live": "", "stalled": "  << STALLED (no progress)",
                "stale": "  << STALE (no heartbeat)", "exited": ""}

# per-dir clock offsets smaller than this are mtime/commit jitter, not
# skew — leave them unapplied so same-host dirs stay byte-stable
CLOCK_OFFSET_MIN_S = 1.0


def quorum_objective() -> float:
    """The QUORUM LOST threshold — the same ``shifu.dcn.quorumFrac``
    the elastic step protocol closes on (parallel/elastic): when fewer
    than this fraction of active processes are still heartbeating, the
    job can no longer close steps by quorum."""
    from ..config import environment
    return environment.get_float("shifu.dcn.quorumFrac", 0.97)


def _quorum_state(recs: List[Dict[str, Any]], counts: Dict[str, int]
                  ) -> Tuple[int, int, float, bool]:
    """(healthy, active, quorum fraction, lost?) — stalled counts as
    heartbeating (a straggler is alive), stale/dead does not."""
    healthy = counts.get("live", 0) + counts.get("stalled", 0)
    active = len(recs) - counts.get("exited", 0)
    quorum = healthy / active if active else 1.0
    return healthy, active, quorum, bool(active) and \
        quorum < quorum_objective()


def _age(rec: Dict[str, Any], now: float) -> float:
    return max(0.0, now - float(rec.get("ts") or 0.0))


def _fmt_count(v: Any) -> str:
    if v is None:
        return "-"
    return f"{v:,.0f}"


def _fmt_quality(v: Any) -> str:
    if v is None:
        return "-"
    return f"{float(v):.4f}"


def fleet_quality(recs: List[Dict[str, Any]]
                  ) -> Optional[Dict[str, Any]]:
    """Merge per-process SERVE quality extras into ONE fleet row: the
    worst (min) live AUC and worst (max) score PSI — per generation and
    overall — summed joined rows, OR'd degradation.  ``None`` when no
    record carries quality extras (plane off fleet-wide)."""
    rows = [r.get("quality") for r in recs if r.get("quality")]
    if not rows:
        return None
    gens: Dict[int, Optional[float]] = {}
    for q in rows:
        for g, auc in (q.get("generations") or {}).items():
            g = int(g)
            if auc is None:
                gens.setdefault(g, None)
            elif gens.get(g) is None:
                gens[g] = float(auc)
            else:
                gens[g] = min(gens[g], float(auc))
    aucs = [float(q["live_auc"]) for q in rows
            if q.get("live_auc") is not None]
    psis = [float(q["score_psi"]) for q in rows
            if q.get("score_psi") is not None]
    return {
        "procs": len(rows),
        "live_auc": round(min(aucs), 6) if aucs else None,
        "score_psi": round(max(psis), 6) if psis else None,
        "joined": sum(int(q.get("joined") or 0) for q in rows),
        "degraded": any(q.get("degraded") for q in rows),
        "generations": {g: (round(gens[g], 6)
                            if gens[g] is not None else None)
                        for g in sorted(gens)},
    }


def status_records(model_set_dir: str, now: Optional[float] = None
                   ) -> Tuple[List[Dict[str, Any]], Dict[str, int]]:
    """(records, state counts) for a model set — each record is the
    health file's content plus ``status`` and ``age_s``."""
    now = time.time() if now is None else now
    recs = read_health(health_dir_for(model_set_dir))
    counts: Dict[str, int] = {}
    for rec in recs:
        rec["status"] = classify(rec, now=now)
        rec["age_s"] = round(_age(rec, now), 3)
        counts[rec["status"]] = counts.get(rec["status"], 0) + 1
    return recs, counts


def _row_flags(rec: Dict[str, Any]) -> str:
    """Staleness + serving-plane flags for one table row."""
    flags = _STATE_FLAGS.get(rec["status"], "")
    slo = rec.get("slo") or {}
    if slo.get("alerting"):
        burns = ",".join(slo.get("alerts") or []) or "burn"
        flags += f"  << SLO BURN ({burns})"
    if rec.get("queue_buildup"):
        flags += "  << QUEUE BUILDUP"
    if rec.get("mode") == "brownout":
        flags += "  << BROWNOUT"
    if (rec.get("quality") or {}).get("degraded"):
        flags += "  << QUALITY DEGRADED"
    return flags


def _row_phase(rec: Dict[str, Any]) -> str:
    phase = rec.get("phase") or "-"
    ingest = [f"{t}:{s}" for t, s in (rec.get("spans") or {}).items()
              if t != "MainThread"]
    if ingest:
        phase += "  [" + " ".join(sorted(ingest)) + "]"
    qd = rec.get("queue_depth")
    if qd is not None:
        phase += f"  q={qd:,.0f}"
    slo = rec.get("slo") or {}
    if slo.get("p99_ms") is not None:
        phase += (f"  p99={slo['p99_ms']:.2f}/"
                  f"{slo.get('objective_p99_ms', 0):.2f}ms")
    return phase


def _render_table(recs: List[Dict[str, Any]], counts: Dict[str, int],
                  with_dir: bool = False) -> List[str]:
    """The per-process table + quorum line (shared by the single-dir and
    aggregate renders)."""
    dir_h = f"{'DIR':<14}" if with_dir else ""
    out = [f"{dir_h}{'PROC':<22}{'STEP':<11}{'STATE':<9}{'AGE':>7}  "
           f"{'ROWS':>12}{'WINDOWS':>9}{'TREES':>7}{'EPOCHS':>7}  PHASE"]
    for rec in recs:
        dir_c = f"{rec.get('_dir_label', '?'):<14}" if with_dir else ""
        out.append(
            f"{dir_c}"
            f"{rec.get('proc', '?'):<22}{(rec.get('step') or '-'):<11}"
            f"{rec['status']:<9}{rec['age_s']:>6.1f}s  "
            f"{_fmt_count(rec.get('rows')):>12}"
            f"{_fmt_count(rec.get('windows')):>9}"
            f"{_fmt_count(rec.get('trees')):>7}"
            f"{_fmt_count(rec.get('epochs')):>7}  {_row_phase(rec)}"
            f"{_row_flags(rec)}")
    for rec in recs:
        rf = rec.get("refresh")
        if rf:
            # the refresh controller's heartbeat extras: lifecycle state,
            # last journalled decision, serving generation + rollback
            # window depth
            out.append(
                f"-- refresh[{rec.get('proc', '?')}]: "
                f"{rf.get('state', '?')}"
                f"  last={rf.get('last_decision') or '-'}"
                f"  outcome={rf.get('last_outcome') or '-'}"
                f"  gen={rf.get('generation', 0)}"
                f" (+{rf.get('generations_held', 0)} held)"
                f"  cycle={rf.get('cycle', 0)}")
    for rec in recs:
        q = rec.get("quality")
        if q:
            # the SERVE heartbeat's compact model-quality summary:
            # rolling live AUC / score PSI over the joined window
            gens = " ".join(
                f"g{g}={_fmt_quality(v)}" for g, v in
                sorted(((int(g), v) for g, v in
                        (q.get("generations") or {}).items())))
            out.append(
                f"-- quality[{rec.get('proc', '?')}]: "
                f"auc={_fmt_quality(q.get('live_auc'))}"
                f"  psi={_fmt_quality(q.get('score_psi'))}"
                f"  joined={int(q.get('joined') or 0):,}"
                + (f"  [{gens}]" if gens else ""))
    healthy, active, quorum, lost = _quorum_state(recs, counts)
    parts = [f"{counts.get(k, 0)} {k}" for k in
             ("live", "stalled", "stale", "exited") if counts.get(k)]
    out.append(f"-- {', '.join(parts) or 'no processes'}; "
               f"quorum {healthy}/{active} ({quorum:.0%}) of active "
               "processes heartbeating")
    if lost:
        out.append(f"-- << QUORUM LOST: {quorum:.0%} heartbeating is "
                   f"below shifu.dcn.quorumFrac "
                   f"{quorum_objective():.2f} — elastic steps can only "
                   "close by timeout; check the stale processes")
    return out


def render_status(model_set_dir: str, now: Optional[float] = None) -> str:
    """One monitor frame: the table + quorum summary."""
    now = time.time() if now is None else now
    recs, counts = status_records(model_set_dir, now=now)
    if not recs:
        return (f"no health records under "
                f"{health_dir_for(model_set_dir)}\n"
                "start a step with telemetry enabled "
                "(SHIFU_TPU_TELEMETRY=1 / --telemetry) to emit heartbeats")
    return "\n".join(_render_table(recs, counts))


def status_json(model_set_dir: str, now: Optional[float] = None
                ) -> Tuple[Dict[str, Any], int]:
    """(one machine-readable snapshot doc, exit code) — the ``monitor
    --once --json`` payload CI/cron scripts consume instead of scraping
    the human table.  Exit 0 when every process is live/exited (or the
    dir is empty: nothing running is not unhealthy); EXIT_UNHEALTHY (3)
    when ANY process is stalled or stale, or any SERVE process reports
    a degraded model-quality verdict."""
    now = time.time() if now is None else now
    recs, counts = status_records(model_set_dir, now=now)
    for rec in recs:
        rec.pop("_file", None)               # host path, not health state
    healthy, active, quorum, lost = _quorum_state(recs, counts)
    fq = fleet_quality(recs)
    unhealthy = counts.get("stalled", 0) + counts.get("stale", 0)
    doc = {
        "kind": "monitor",
        "schema_version": tracer.SCHEMA_VERSION,
        "ts": round(now, 3),
        "health_dir": health_dir_for(model_set_dir),
        "procs": recs,
        "quality": fq,
        "summary": {
            "total": len(recs),
            "counts": {k: counts.get(k, 0)
                       for k in ("live", "stalled", "stale", "exited")},
            "active": active,
            "healthy": healthy,
            "quorum": round(quorum, 4),
            "quorum_lost": lost,
        },
    }
    degraded = bool(fq and fq["degraded"])
    return doc, (EXIT_UNHEALTHY if unhealthy or lost or degraded else 0)


# ------------------------------------------------- cross-process merge
def record_clock_offset(rec: Dict[str, Any]) -> float:
    """Writer-clock minus fileserver-clock estimate for one health
    record: the embedded ``ts`` and the file mtime stamp the SAME atomic
    commit, so their difference is the writer's clock offset (plus
    commit jitter — see CLOCK_OFFSET_MIN_S)."""
    path = rec.get("_file")
    if not path:
        return 0.0
    try:
        return float(rec.get("ts") or 0.0) - os.path.getmtime(path)
    except OSError:
        return 0.0


def dir_clock_offset(model_set_dir: str) -> float:
    """The dir-level clock offset (median over its health records);
    offsets under CLOCK_OFFSET_MIN_S collapse to 0 (jitter, not skew)."""
    offs = sorted(record_clock_offset(r)
                  for r in read_health(health_dir_for(model_set_dir)))
    if not offs:
        return 0.0
    off = offs[len(offs) // 2]
    return off if abs(off) >= CLOCK_OFFSET_MIN_S else 0.0


def aggregate_records(dirs: Sequence[str], now: Optional[float] = None
                      ) -> Tuple[List[Dict[str, Any]], Dict[str, int]]:
    """Merged, clock-normalized health records across N telemetry dirs.
    Each record gains ``_dir`` / ``_dir_label`` / ``clock_offset_s``;
    ages and staleness are computed on the NORMALIZED timestamps so a
    skewed-clock host is not misread as stale (or freshly alive)."""
    now = time.time() if now is None else now
    recs: List[Dict[str, Any]] = []
    counts: Dict[str, int] = {}
    for d in dirs:
        off = dir_clock_offset(d)
        label = os.path.basename(os.path.abspath(d))
        for rec in read_health(health_dir_for(d)):
            if off:
                for key in ("ts", "started_ts", "last_progress_ts"):
                    if rec.get(key):
                        rec[key] = float(rec[key]) - off
            rec["_dir"] = d
            rec["_dir_label"] = label
            rec["clock_offset_s"] = round(off, 3)
            rec["status"] = classify(rec, now=now)
            rec["age_s"] = round(_age(rec, now), 3)
            counts[rec["status"]] = counts.get(rec["status"], 0) + 1
            recs.append(rec)
    recs.sort(key=lambda r: (r.get("_dir_label") or "",
                             r.get("proc") or ""))
    return recs, counts


def step_lag_table(recs: List[Dict[str, Any]],
                   now: Optional[float] = None) -> List[Dict[str, Any]]:
    """Per-proc lag against the front-runner of its step: rows behind
    the max-progress process and seconds since the proc last advanced,
    on clock-normalized timestamps — the per-worker lag signal quorum/
    straggler logic consumes (ROADMAP #3)."""
    now = time.time() if now is None else now
    by_step: Dict[str, List[Dict[str, Any]]] = {}
    for rec in recs:
        by_step.setdefault(rec.get("step") or "-", []).append(rec)
    out: List[Dict[str, Any]] = []
    for step in sorted(by_step):
        group = by_step[step]
        max_rows = max(float(r.get("rows") or 0.0) for r in group)
        max_prog = max(float(r.get("last_progress_ts") or 0.0)
                       for r in group)
        for r in group:
            rows = float(r.get("rows") or 0.0)
            prog = float(r.get("last_progress_ts") or 0.0)
            out.append({
                "step": step,
                "proc": r.get("proc"),
                "dir": r.get("_dir_label") or r.get("_dir"),
                "status": r.get("status"),
                "rows": rows,
                "rows_lag": max_rows - rows,
                "lag_s": round(max_prog - prog, 3) if prog else None,
                "progress_age_s": round(now - prog, 3) if prog else None,
                "clock_offset_s": r.get("clock_offset_s", 0.0),
            })
    return out


def render_aggregate(dirs: Sequence[str],
                     now: Optional[float] = None) -> str:
    """One merged monitor frame over N telemetry dirs: the tagged
    table, merged quorum, and the per-proc step-lag table."""
    now = time.time() if now is None else now
    recs, counts = aggregate_records(dirs, now=now)
    if not recs:
        return ("no health records under any of: "
                + ", ".join(health_dir_for(d) for d in dirs))
    out = [f"== merged monitor over {len(dirs)} telemetry dir(s)"]
    out += _render_table(recs, counts, with_dir=True)
    fq = fleet_quality(recs)
    if fq:
        gens = " ".join(f"g{g}={_fmt_quality(v)}"
                        for g, v in sorted(fq["generations"].items()))
        out.append(
            f"-- fleet quality ({fq['procs']} proc(s)): "
            f"worst auc={_fmt_quality(fq['live_auc'])}"
            f"  worst psi={_fmt_quality(fq['score_psi'])}"
            f"  joined={fq['joined']:,}"
            + (f"  [{gens}]" if gens else "")
            + ("  << QUALITY DEGRADED" if fq["degraded"] else ""))
    out.append("")
    out.append("-- per-proc step lag (vs the step's front-runner)")
    out.append(f"{'STEP':<11}{'PROC':<22}{'DIR':<14}{'ROWS':>12}"
               f"{'LAG(rows)':>11}{'LAG(s)':>8}{'CLKOFF(s)':>10}")
    for row in step_lag_table(recs, now=now):
        lag_s = f"{row['lag_s']:.1f}" if row["lag_s"] is not None else "-"
        out.append(
            f"{row['step']:<11}{(row['proc'] or '?'):<22}"
            f"{(row['dir'] or '?'):<14}{_fmt_count(row['rows']):>12}"
            f"{_fmt_count(row['rows_lag']):>11}{lag_s:>8}"
            f"{row['clock_offset_s']:>10.1f}")
    return "\n".join(out)


def aggregate_json(dirs: Sequence[str], now: Optional[float] = None
                   ) -> Tuple[Dict[str, Any], int]:
    """The machine-readable merge (``monitor --aggregate --once
    --json``): per-proc health + merged quorum + the step-lag table;
    exit code semantics match :func:`status_json`."""
    now = time.time() if now is None else now
    recs, counts = aggregate_records(dirs, now=now)
    lag = step_lag_table(recs, now=now)
    for rec in recs:
        rec.pop("_file", None)
        rec.pop("_dir", None)
    healthy, active, quorum, lost = _quorum_state(recs, counts)
    fq = fleet_quality(recs)
    unhealthy = counts.get("stalled", 0) + counts.get("stale", 0)
    doc = {
        "kind": "monitor_aggregate",
        "schema_version": tracer.SCHEMA_VERSION,
        "ts": round(now, 3),
        "dirs": [os.path.abspath(d) for d in dirs],
        "clock_offsets": {os.path.basename(os.path.abspath(d)):
                          round(dir_clock_offset(d), 3) for d in dirs},
        "procs": recs,
        "step_lag": lag,
        "quality": fq,
        "summary": {
            "total": len(recs),
            "counts": {k: counts.get(k, 0)
                       for k in ("live", "stalled", "stale", "exited")},
            "active": active,
            "healthy": healthy,
            "quorum": round(quorum, 4),
            "quorum_lost": lost,
        },
    }
    degraded = bool(fq and fq["degraded"])
    return doc, (EXIT_UNHEALTHY if unhealthy or lost or degraded else 0)


def run_monitor(model_set_dir: str, interval_s: float = 2.0,
                once: bool = False, max_frames: Optional[int] = None,
                json_mode: bool = False,
                aggregate_dirs: Optional[Sequence[str]] = None,
                _print=print) -> int:
    """The CLI loop: render a frame every ``interval_s`` until
    interrupted (``--once`` renders a single frame).  The single-dir
    human table always exits 0 — an empty health dir is a message, not
    an error; ``json_mode`` prints one JSON doc per frame and carries
    the health exit code (0 ok / 3 any stalled-or-stale or QUORUM
    LOST) so scripts can gate on it.  ``aggregate_dirs`` switches to
    the merged multi-dir view (``--aggregate``; replaces ``--dir``);
    its human table ALSO exits 3 when the quorum is lost (live members
    below ``shifu.dcn.quorumFrac``) or the merged fleet quality row is
    degraded — the fleet-level page."""
    frames = 0
    rc = 0
    try:
        while True:
            if aggregate_dirs:
                if json_mode:
                    doc, rc = aggregate_json(aggregate_dirs)
                    _print(json.dumps(doc, sort_keys=True))
                else:
                    _print(render_aggregate(aggregate_dirs))
                    recs, counts = aggregate_records(aggregate_dirs)
                    fq = fleet_quality(recs)
                    rc = EXIT_UNHEALTHY \
                        if (_quorum_state(recs, counts)[3]
                            or (fq and fq["degraded"])) else 0
            elif json_mode:
                doc, rc = status_json(model_set_dir)
                _print(json.dumps(doc, sort_keys=True))
            else:
                _print(render_status(model_set_dir))
            frames += 1
            if once or (max_frames is not None and frames >= max_frames):
                return rc if (json_mode or aggregate_dirs) else 0
            _print("")
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return rc if (json_mode or aggregate_dirs) else 0
