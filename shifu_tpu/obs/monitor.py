"""``shifu-tpu monitor`` — tail the health directory, render live status.

Reads the heartbeat files :mod:`obs.health` writers commit under
``<modelset>/telemetry/health/`` and renders one line per process:
step, state (live / stalled / stale / exited), heartbeat age, the phase
each thread is in right now, and the progress counters (rows, windows,
trees, epochs).  The summary line carries the quorum fraction —
``healthy / total`` — the primitive ROADMAP #3's straggler/quorum logic
reads.

Stateless by design: every render is a fresh read of the directory, so
the monitor can attach to (and detach from) a running job at any time,
from any process, with no coordination.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Tuple

from . import tracer
from .health import classify, health_dir_for, read_health

# `monitor --once --json` exit code when any process is stalled/stale —
# distinct from generic failure (1) and the bench/schema mismatch (2)
EXIT_UNHEALTHY = 3

_STATE_FLAGS = {"live": "", "stalled": "  << STALLED (no progress)",
                "stale": "  << STALE (no heartbeat)", "exited": ""}


def _age(rec: Dict[str, Any], now: float) -> float:
    return max(0.0, now - float(rec.get("ts") or 0.0))


def _fmt_count(v: Any) -> str:
    if v is None:
        return "-"
    return f"{v:,.0f}"


def status_records(model_set_dir: str, now: Optional[float] = None
                   ) -> Tuple[List[Dict[str, Any]], Dict[str, int]]:
    """(records, state counts) for a model set — each record is the
    health file's content plus ``status`` and ``age_s``."""
    now = time.time() if now is None else now
    recs = read_health(health_dir_for(model_set_dir))
    counts: Dict[str, int] = {}
    for rec in recs:
        rec["status"] = classify(rec, now=now)
        rec["age_s"] = round(_age(rec, now), 3)
        counts[rec["status"]] = counts.get(rec["status"], 0) + 1
    return recs, counts


def render_status(model_set_dir: str, now: Optional[float] = None) -> str:
    """One monitor frame: the table + quorum summary."""
    now = time.time() if now is None else now
    recs, counts = status_records(model_set_dir, now=now)
    if not recs:
        return (f"no health records under "
                f"{health_dir_for(model_set_dir)}\n"
                "start a step with telemetry enabled "
                "(SHIFU_TPU_TELEMETRY=1 / --telemetry) to emit heartbeats")
    out = [f"{'PROC':<22}{'STEP':<11}{'STATE':<9}{'AGE':>7}  "
           f"{'ROWS':>12}{'WINDOWS':>9}{'TREES':>7}{'EPOCHS':>7}  PHASE"]
    for rec in recs:
        phase = rec.get("phase") or "-"
        ingest = [f"{t}:{s}" for t, s in (rec.get("spans") or {}).items()
                  if t != "MainThread"]
        if ingest:
            phase += "  [" + " ".join(sorted(ingest)) + "]"
        out.append(
            f"{rec.get('proc', '?'):<22}{(rec.get('step') or '-'):<11}"
            f"{rec['status']:<9}{rec['age_s']:>6.1f}s  "
            f"{_fmt_count(rec.get('rows')):>12}"
            f"{_fmt_count(rec.get('windows')):>9}"
            f"{_fmt_count(rec.get('trees')):>7}"
            f"{_fmt_count(rec.get('epochs')):>7}  {phase}"
            f"{_STATE_FLAGS.get(rec['status'], '')}")
    healthy = counts.get("live", 0) + counts.get("stalled", 0)
    active = len(recs) - counts.get("exited", 0)
    parts = [f"{counts.get(k, 0)} {k}" for k in
             ("live", "stalled", "stale", "exited") if counts.get(k)]
    quorum = healthy / active if active else 1.0
    out.append(f"-- {', '.join(parts) or 'no processes'}; "
               f"quorum {healthy}/{active} ({quorum:.0%}) of active "
               "processes heartbeating")
    return "\n".join(out)


def status_json(model_set_dir: str, now: Optional[float] = None
                ) -> Tuple[Dict[str, Any], int]:
    """(one machine-readable snapshot doc, exit code) — the ``monitor
    --once --json`` payload CI/cron scripts consume instead of scraping
    the human table.  Exit 0 when every process is live/exited (or the
    dir is empty: nothing running is not unhealthy); EXIT_UNHEALTHY (3)
    when ANY process is stalled or stale."""
    now = time.time() if now is None else now
    recs, counts = status_records(model_set_dir, now=now)
    for rec in recs:
        rec.pop("_file", None)               # host path, not health state
    healthy = counts.get("live", 0) + counts.get("stalled", 0)
    active = len(recs) - counts.get("exited", 0)
    unhealthy = counts.get("stalled", 0) + counts.get("stale", 0)
    doc = {
        "kind": "monitor",
        "schema_version": tracer.SCHEMA_VERSION,
        "ts": round(now, 3),
        "health_dir": health_dir_for(model_set_dir),
        "procs": recs,
        "summary": {
            "total": len(recs),
            "counts": {k: counts.get(k, 0)
                       for k in ("live", "stalled", "stale", "exited")},
            "active": active,
            "healthy": healthy,
            "quorum": round(healthy / active, 4) if active else 1.0,
        },
    }
    return doc, (EXIT_UNHEALTHY if unhealthy else 0)


def run_monitor(model_set_dir: str, interval_s: float = 2.0,
                once: bool = False, max_frames: Optional[int] = None,
                json_mode: bool = False, _print=print) -> int:
    """The CLI loop: render a frame every ``interval_s`` until
    interrupted (``--once`` renders a single frame).  The human table
    always exits 0 — an empty health dir is a message, not an error;
    ``json_mode`` prints one JSON doc per frame and carries the health
    exit code (0 ok / 3 any stalled-or-stale) so scripts can gate on
    it."""
    frames = 0
    rc = 0
    try:
        while True:
            if json_mode:
                doc, rc = status_json(model_set_dir)
                _print(json.dumps(doc, sort_keys=True))
            else:
                _print(render_status(model_set_dir))
            frames += 1
            if once or (max_frames is not None and frames >= max_frames):
                return rc if json_mode else 0
            _print("")
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return rc if json_mode else 0
