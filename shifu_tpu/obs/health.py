"""Live health & heartbeats — per-process liveness for long-running steps.

The post-hoc trace (``trace.jsonl``) tells you what happened; this module
tells you what is happening NOW.  Every pipeline step process runs a
:class:`HeartbeatWriter`: a daemon thread that, every ``interval_s``
seconds, snapshots the process's live state — current step, open spans
(per thread: the main step phase AND the ingest prep thread), rows /
windows / trees / epochs out of the metrics registry, device-memory
high-water, a last-progress timestamp — and atomically commits it to
``<modelset>/telemetry/health/<proc>.json`` through :mod:`ioutil` (a
reader, or a crash, never observes a torn health file).

This is the per-worker progress surface the reference's Guagua master
aggregated from worker RPC (``GuaguaConstants`` progress reporting): the
``shifu-tpu monitor`` CLI (:mod:`obs.monitor`) tails the directory and
flags stale/stalled processes, and ROADMAP #3's straggler/quorum logic is
meant to read the same files.

Staleness model (shared with the monitor via :func:`classify`):

- ``live``     heartbeat age <= STALE_FACTOR x the file's own declared
  interval and the process reports progress recently;
- ``stalled``  heartbeats fresh but no progress-counter movement for
  ``stall_after_s`` (the straggler flag — the process is alive but its
  plane stopped advancing: stuck collective, dead input, livelock);
- ``stale``    heartbeat age > STALE_FACTOR x interval — SIGSTOP'd,
  deadlocked, or dead without a final beat (OOM-kill, preemption);
- ``exited``   the process committed a final beat with its exit code.

Zero-cost when telemetry is disabled: :func:`start_heartbeat` returns
``None`` without creating a thread, a file, or a directory.

Fault site: ``obs:heartbeat=<beat>`` fires before beat ``<beat>``'s
atomic commit — a ``kill`` there proves a death mid-heartbeat leaves the
previous (valid) file in place, never a torn one.
"""

from __future__ import annotations

import json
import logging
import os
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from .. import faults
from ..ioutil import atomic_write_json, sweep_orphan_tmp
from . import registry, tracer

log = logging.getLogger(__name__)

HEALTH_DIRNAME = "health"
# heartbeat files older than STALE_FACTOR x their declared interval are
# stale — "within 2 heartbeat intervals", the monitor acceptance bound
STALE_FACTOR = 2.0

# registry counters folded into the headline progress fields; ANY counter
# movement refreshes last_progress_ts, these just get first-class columns
_ROWS_COUNTERS = ("stats.rows", "norm.rows", "eval.rows_scored",
                  "ingest.rows_emitted")
_PROGRESS_FIELDS = (("windows", "ingest.windows_emitted"),
                    ("trees", "train.trees"),
                    ("epochs", "train.epochs"))


def heartbeat_interval_s(override: Optional[float] = None) -> float:
    """Heartbeat cadence: explicit override > env ``SHIFU_TPU_HEARTBEAT_S``
    > property ``shifu.telemetry.heartbeatSeconds`` > 5 s."""
    if override is not None:
        return max(0.05, float(override))
    v = os.environ.get("SHIFU_TPU_HEARTBEAT_S")
    if v:
        try:
            return max(0.05, float(v))
        except ValueError:
            pass
    from ..config import environment
    p = environment.get_property("shifu.telemetry.heartbeatSeconds")
    if p is not None:
        try:
            return max(0.05, float(p))
        except (TypeError, ValueError):
            pass
    return 5.0


def health_dir_for(model_set_dir: str) -> str:
    return os.path.join(os.path.abspath(model_set_dir), "telemetry",
                        HEALTH_DIRNAME)


class HeartbeatWriter:
    """Background heartbeat thread for ONE process; see module docs."""

    def __init__(self, health_dir: str, step: Optional[str] = None,
                 proc: Optional[str] = None,
                 interval_s: Optional[float] = None,
                 extras_fn: Optional[Callable[[], Dict[str, Any]]] = None):
        self.health_dir = health_dir
        self.step = step
        self.pid = os.getpid()
        self.proc = proc or f"{(step or 'proc').lower()}-{self.pid}"
        self.interval_s = heartbeat_interval_s(interval_s)
        self.path = os.path.join(health_dir, f"{self.proc}.json")
        # per-beat extra fields (the serve plane's queue_depth /
        # queue_buildup / slo summary); failures are swallowed — a
        # broken extras hook must never stop the heartbeat
        self._extras_fn = extras_fn
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._started_ts = 0.0
        self._beats = 0
        self._last_progress_ts = 0.0
        self._last_counter_total: Optional[float] = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "HeartbeatWriter":
        os.makedirs(self.health_dir, exist_ok=True)
        sweep_orphan_tmp(self.health_dir)   # a prior crash's .tmp droppings
        self._started_ts = time.time()
        self._last_progress_ts = self._started_ts
        self.beat()                          # beat 0: visible immediately
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="shifu-heartbeat")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.beat()
            except Exception:               # telemetry must never fail a step
                log.debug("heartbeat write failed", exc_info=True)

    def stop(self, exit_code: Optional[int] = None) -> None:
        """Retire the thread and commit a final ``state=exited`` beat so
        the monitor distinguishes a clean exit from a silent death."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(2.0, 2 * self.interval_s))
            self._thread = None
        try:
            self.beat(state="exited", exit_code=exit_code)
        except Exception:
            log.debug("final heartbeat write failed", exc_info=True)

    # ------------------------------------------------------------- one beat
    def beat(self, state: str = "running",
             exit_code: Optional[int] = None) -> Dict[str, Any]:
        rec = self._record(state, exit_code)
        faults.fire("obs", "heartbeat", self._beats, path=self.path)
        atomic_write_json(self.path, rec, indent=1)
        self._beats += 1
        return rec

    def _record(self, state: str,
                exit_code: Optional[int]) -> Dict[str, Any]:
        now = time.time()
        metrics = {m["name"]: m for m in registry.snapshot(reset=False)}
        counter_total = sum(m.get("value") or 0.0 for m in metrics.values()
                            if m.get("type") == "counter")
        if self._last_counter_total is None \
                or counter_total != self._last_counter_total:
            self._last_progress_ts = now
            self._last_counter_total = counter_total
        # per-thread deepest open span: what each thread is doing NOW
        spans: Dict[str, str] = {}
        for sp in tracer.live_spans():      # oldest first -> deepest wins
            spans[sp["thread"]] = sp["name"]
        registry.sample_device_memory()
        rec: Dict[str, Any] = {
            "kind": "health",
            "schema_version": tracer.SCHEMA_VERSION,
            "proc": self.proc,
            "pid": self.pid,
            "host": socket.gethostname(),
            "step": self.step,
            "state": state,
            "ts": round(now, 3),
            "started_ts": round(self._started_ts, 3),
            "interval_s": self.interval_s,
            "beat": self._beats,
            "phase": spans.get("MainThread"),
            "spans": spans,
            "rows": sum(metrics[n]["value"] for n in _ROWS_COUNTERS
                        if n in metrics),
            "last_progress_ts": round(self._last_progress_ts, 3),
        }
        for field, metric in _PROGRESS_FIELDS:
            if metric in metrics:
                rec[field] = metrics[metric]["value"]
        hbm = metrics.get("device.peak_bytes_in_use")
        if hbm and hbm.get("value") is not None:
            rec["device_peak_bytes"] = hbm["value"]
        if exit_code is not None:
            rec["exit_code"] = exit_code
        if self._extras_fn is not None:
            try:
                extras = self._extras_fn() or {}
            except Exception:
                log.debug("heartbeat extras hook failed", exc_info=True)
                extras = {}
            for k, v in extras.items():      # core fields always win
                rec.setdefault(k, v)
        return rec


def start_heartbeat(health_dir: str, step: Optional[str] = None,
                    proc: Optional[str] = None,
                    interval_s: Optional[float] = None,
                    extras_fn: Optional[Callable[[], Dict[str, Any]]] = None
                    ) -> Optional[HeartbeatWriter]:
    """Start the per-process heartbeat — ``None`` (no thread, no file, no
    directory) when telemetry is disabled."""
    if not tracer.enabled():
        return None
    return HeartbeatWriter(health_dir, step=step, proc=proc,
                           interval_s=interval_s,
                           extras_fn=extras_fn).start()


# ---------------------------------------------------------------- readers
def read_health(health_dir: str) -> List[Dict[str, Any]]:
    """All parseable health records under ``health_dir``, sorted by proc.
    Unparseable files are skipped with a warning (atomic writes make torn
    files impossible; a half-copied directory should not kill the
    monitor)."""
    out: List[Dict[str, Any]] = []
    try:
        names = sorted(os.listdir(health_dir))
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        path = os.path.join(health_dir, name)
        try:
            with open(path) as f:
                rec = json.load(f)
        except (OSError, json.JSONDecodeError):
            log.warning("skipping unparseable health file %s", path)
            continue
        if isinstance(rec, dict):
            rec["_file"] = path
            out.append(rec)
    return out


def classify(rec: Dict[str, Any], now: Optional[float] = None,
             stall_after_s: Optional[float] = None) -> str:
    """``live | stalled | stale | exited`` for one health record (see
    module docs for the model)."""
    now = time.time() if now is None else now
    if rec.get("state") == "exited":
        return "exited"
    interval = float(rec.get("interval_s") or 5.0)
    age = now - float(rec.get("ts") or 0.0)
    if age > STALE_FACTOR * interval:
        return "stale"
    if stall_after_s is None:
        stall_after_s = max(6 * interval, 30.0)
    if now - float(rec.get("last_progress_ts") or 0.0) > stall_after_s:
        return "stalled"
    return "live"
