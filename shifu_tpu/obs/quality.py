"""Streaming model-quality monitor: live AUC, calibration, score-PSI.

The reference's ``posttrain`` step measures the score distribution once,
offline; this module closes the production loop the way a serving
system must (the large-scale-ML-systems argument: quality is measured
where the model serves, not where it trained):

- **score-PSI** — a fixed-bin histogram of live scores per model
  generation vs the training-time snapshot eval persists as
  ``telemetry/posttrain.json`` (:func:`write_posttrain_snapshot`), the
  exact PSI the drift plane computes for inputs, applied to OUTPUTS;
- **calibration** — reliability bins (mean predicted probability vs
  observed positive rate) and their expected calibration error over the
  joined windows;
- **live AUC** — rolling AUC over joined ``(score, label)`` windows
  (:mod:`shifu_tpu.eval.metrics`' sweep — the same math offline eval
  uses), attributed PER GENERATION so a hot-swap shows old-vs-new live
  AUC side by side.

Degradation is judged on the CURRENT generation once ``minJoined`` rows
have joined: live AUC more than ``-Dshifu.quality.aucDelta`` below the
snapshot AUC, or score-PSI at/over ``-Dshifu.quality.psiThreshold``
(default: the drift threshold).  The refresh controller reads
``summary()`` as its third trigger source; the monitor/report planes
render the same dict from the ``telemetry/quality.json`` artifact.

Zero-cost when off: the plane only exists when
``-Dshifu.scorelog.sampleRate`` > 0 (:func:`start_quality_monitor`
returns ``None`` otherwise) — no histograms, no windows, no artifact.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..ioutil import atomic_write_json
from ..ops.stats_math import psi
from . import registry, tracer

log = logging.getLogger(__name__)

POSTTRAIN_BASENAME = "posttrain.json"
QUALITY_BASENAME = "quality.json"

SCORE_BINS = 10                  # PSI + reliability bins over [lo, hi]
DEFAULT_AUC_DELTA = 0.05
DEFAULT_MIN_JOINED = 64
# per-generation rolling window bound on joined rows (memory, and how
# fast the live AUC forgets)
WINDOW_ROWS = 4096


def posttrain_snapshot_path(model_set_dir: str) -> str:
    return os.path.join(model_set_dir, "telemetry", POSTTRAIN_BASENAME)


def quality_artifact_path(model_set_dir: str) -> str:
    return os.path.join(model_set_dir, "telemetry", QUALITY_BASENAME)


def quality_auc_delta(override: Optional[float] = None) -> float:
    if override is not None:
        return float(override)
    from ..config import environment
    p = environment.get_property("shifu.quality.aucDelta")
    if p is not None:
        try:
            return float(p)
        except (TypeError, ValueError):
            pass
    return DEFAULT_AUC_DELTA


def quality_psi_threshold(override: Optional[float] = None) -> float:
    if override is not None:
        return float(override)
    from ..config import environment
    p = environment.get_property("shifu.quality.psiThreshold")
    if p is not None:
        try:
            return float(p)
        except (TypeError, ValueError):
            pass
    from .drift import psi_threshold
    return psi_threshold()


def quality_min_joined(override: Optional[int] = None) -> int:
    if override is not None:
        return int(override)
    from ..config import environment
    p = environment.get_property("shifu.quality.minJoined")
    if p is not None:
        try:
            return int(p)
        except (TypeError, ValueError):
            pass
    return DEFAULT_MIN_JOINED


def _score_histogram(scores: np.ndarray, lo: float, hi: float,
                     bins: int = SCORE_BINS) -> np.ndarray:
    span = max(hi - lo, 1e-12)
    idx = np.clip(((np.asarray(scores, np.float64) - lo) / span
                   * bins).astype(np.int64), 0, bins - 1)
    return np.bincount(idx, minlength=bins).astype(np.float64)


def write_posttrain_snapshot(path: str, scores, auc: Optional[float],
                             scale: Optional[float] = None
                             ) -> Dict[str, Any]:
    """The training-time score snapshot (the posttrain analogue) the
    live plane compares against: offline AUC + the score histogram over
    the observed range.  Written atomically by eval; ``scale`` is the
    scorer's score scale (probability = score / scale)."""
    if scale is None:
        from ..eval.scorer import SCORE_SCALE
        scale = SCORE_SCALE
    s = np.asarray(scores, np.float64).ravel()
    lo = float(s.min()) if s.size else 0.0
    hi = float(s.max()) if s.size else 1.0
    doc = {
        "kind": "posttrain",
        "schema_version": tracer.SCHEMA_VERSION,
        "ts": round(time.time(), 3),
        "rows": int(s.size),
        "auc": None if auc is None else round(float(auc), 6),
        "score_scale": float(scale),
        "score_lo": round(lo, 6),
        "score_hi": round(hi, 6),
        "score_hist": [int(c) for c in _score_histogram(s, lo, hi)],
    }
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    atomic_write_json(path, doc)
    return doc


def load_posttrain_snapshot(model_set_dir: str) -> Optional[Dict[str, Any]]:
    path = posttrain_snapshot_path(model_set_dir)
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


class _GenWindow:
    """One generation's live state: score histogram (every sampled
    score) + bounded joined (score, label) window."""

    __slots__ = ("scored", "hist", "scores", "labels", "joined")

    def __init__(self, bins: int):
        self.scored = 0
        self.hist = np.zeros(bins, np.float64)
        self.scores: List[np.ndarray] = []
        self.labels: List[np.ndarray] = []
        self.joined = 0

    def trim(self, cap: int) -> None:
        while self.joined > cap and len(self.scores) > 1:
            self.joined -= int(len(self.scores.pop(0)))
            self.labels.pop(0)


class QualityMonitor:
    """Per-generation live quality over the score-log feed.

    ``observe_scores`` takes EVERY sampled score (the PSI feed);
    ``update`` takes only joined rows (the AUC/calibration feed).
    Both are a few numpy ops per call — safe on the serve path at the
    sample rates the score log is meant for.
    """

    def __init__(self, snapshot: Optional[Dict[str, Any]] = None,
                 psi_threshold: Optional[float] = None,
                 auc_delta: Optional[float] = None,
                 min_joined: Optional[int] = None,
                 window_rows: int = WINDOW_ROWS):
        self.snapshot = snapshot
        self.psi_threshold = quality_psi_threshold(psi_threshold)
        self.auc_delta = quality_auc_delta(auc_delta)
        self.min_joined = quality_min_joined(min_joined)
        self.window_rows = int(window_rows)
        snap = snapshot or {}
        self.baseline_auc = snap.get("auc")
        self._lo = float(snap.get("score_lo", 0.0))
        self._hi = float(snap.get("score_hi", 1.0))
        self._scale = float(snap.get("score_scale", 1.0)) or 1.0
        self._expected = (np.asarray(snap["score_hist"], np.float64)
                          if snap.get("score_hist") else None)
        self._gens: Dict[int, _GenWindow] = {}

    def _gen(self, gen: int) -> _GenWindow:
        w = self._gens.get(int(gen))
        if w is None:
            w = self._gens[int(gen)] = _GenWindow(SCORE_BINS)
        return w

    # ------------------------------------------------------------- feeds
    def observe_scores(self, gen: int, scores) -> None:
        s = np.asarray(scores, np.float64).ravel()
        if not s.size:
            return
        w = self._gen(gen)
        w.scored += int(s.size)
        w.hist += _score_histogram(s, self._lo, self._hi)

    def update(self, gen: int, scores, labels, weights=None) -> None:
        s = np.asarray(scores, np.float32).ravel()
        lab = np.asarray(labels, np.float32).ravel()
        if not s.size:
            return
        w = self._gen(gen)
        w.scores.append(s)
        w.labels.append(lab)
        w.joined += int(s.size)
        w.trim(self.window_rows)

    def reset_windows(self) -> None:
        """Fresh windows (kept snapshot/thresholds) — the refresh
        controller calls this after a cycle so a just-promoted model is
        judged only on its own traffic."""
        self._gens = {}

    # ----------------------------------------------------------- read-out
    def _gen_row(self, w: _GenWindow) -> Dict[str, Any]:
        live_auc = ece = None
        if w.joined >= max(self.min_joined, 1):
            s = np.concatenate(w.scores)
            lab = np.concatenate(w.labels)
            if 0.0 < float(lab.mean()) < 1.0:   # both classes present
                from ..eval.metrics import auc_trapezoid, sweep
                c = sweep(s, lab)
                live_auc = float(auc_trapezoid(
                    c.fp / max(c.neg_total, 1e-12),
                    c.tp / max(c.pos_total, 1e-12)))
                ece = self._ece(s, lab)
        p = None
        if self._expected is not None and w.hist.sum() > 0:
            p = float(psi(self._expected, w.hist))
        return {"scored": w.scored, "joined": w.joined,
                "live_auc": None if live_auc is None
                else round(live_auc, 6),
                "ece": None if ece is None else round(ece, 6),
                "psi": None if p is None else round(p, 6)}

    def _ece(self, scores: np.ndarray, labels: np.ndarray) -> float:
        """Reliability-bin expected calibration error: |mean predicted
        probability - observed positive rate| weighted by bin mass."""
        prob = np.clip(np.asarray(scores, np.float64) / self._scale,
                       0.0, 1.0)
        idx = np.clip((prob * SCORE_BINS).astype(np.int64), 0,
                      SCORE_BINS - 1)
        n = np.bincount(idx, minlength=SCORE_BINS).astype(np.float64)
        p_sum = np.bincount(idx, weights=prob, minlength=SCORE_BINS)
        y_sum = np.bincount(idx, weights=labels.astype(np.float64),
                            minlength=SCORE_BINS)
        mask = n > 0
        return float(np.sum(np.abs(p_sum[mask] - y_sum[mask]))
                     / max(n.sum(), 1.0))

    def summary(self) -> Dict[str, Any]:
        gens = {str(g): self._gen_row(w)
                for g, w in sorted(self._gens.items())}
        cur = max(self._gens) if self._gens else None
        row = gens[str(cur)] if cur is not None else {}
        reasons = []
        if (row.get("live_auc") is not None
                and self.baseline_auc is not None
                and self.baseline_auc - row["live_auc"]
                >= self.auc_delta):
            reasons.append("live-auc")
        if (row.get("psi") is not None
                and row.get("scored", 0) >= max(self.min_joined, 1)
                and row["psi"] >= self.psi_threshold):
            reasons.append("score-psi")
        return {
            "kind": "quality",
            "schema_version": tracer.SCHEMA_VERSION,
            "ts": round(time.time(), 3),
            "baseline_auc": self.baseline_auc,
            "auc_delta": self.auc_delta,
            "psi_threshold": self.psi_threshold,
            "min_joined": self.min_joined,
            "current_gen": cur,
            "live_auc": row.get("live_auc"),
            "score_psi": row.get("psi"),
            "ece": row.get("ece"),
            "joined": row.get("joined", 0),
            "generations": gens,
            "degraded": bool(reasons),
            "reasons": reasons,
        }

    def compact(self) -> Dict[str, Any]:
        """The heartbeat-extras shape (small: every beat carries it)."""
        summ = self.summary()
        return {"degraded": summ["degraded"],
                "live_auc": summ["live_auc"],
                "score_psi": summ["score_psi"],
                "joined": summ["joined"],
                "generations": {g: r["live_auc"]
                                for g, r in summ["generations"].items()}}

    def emit(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Publish: ``quality.*`` gauges into the registry and, when
        ``path`` is given, the full table as ``quality.json``
        (atomic)."""
        summ = self.summary()
        registry.gauge("quality.scored_rows").set(
            sum(w.scored for w in self._gens.values()))
        registry.gauge("quality.joined_rows").set(
            sum(w.joined for w in self._gens.values()))
        registry.gauge("quality.degraded").set(
            1.0 if summ["degraded"] else 0.0)
        if summ["live_auc"] is not None:
            registry.gauge("quality.live_auc").set(summ["live_auc"])
        if summ["score_psi"] is not None:
            registry.gauge("quality.score_psi").set(summ["score_psi"])
        if summ["ece"] is not None:
            registry.gauge("quality.ece").set(summ["ece"])
        if path:
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                atomic_write_json(path, summ)
            except OSError:
                log.warning("quality table write failed", exc_info=True)
        return summ


def start_quality_monitor(model_set_dir: Optional[str] = None,
                          snapshot: Optional[Dict[str, Any]] = None,
                          sample_rate: Optional[float] = None,
                          psi_threshold: Optional[float] = None,
                          auc_delta: Optional[float] = None,
                          min_joined: Optional[int] = None
                          ) -> Optional[QualityMonitor]:
    """A monitor seeded from the model set's posttrain snapshot —
    ``None`` when the score log is off (no feed to monitor).  Without a
    snapshot the monitor still tracks live AUC/ECE; PSI and the AUC
    baseline need the artifact."""
    from .scorelog import scorelog_sample_rate
    if scorelog_sample_rate(sample_rate) <= 0.0:
        return None
    if snapshot is None and model_set_dir:
        snapshot = load_posttrain_snapshot(model_set_dir)
    return QualityMonitor(snapshot=snapshot,
                          psi_threshold=psi_threshold,
                          auc_delta=auc_delta, min_joined=min_joined)
