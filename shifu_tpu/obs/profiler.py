"""Profiler hook — opt-in ``jax.profiler.trace()`` capture per step.

``shifu-tpu <step> --profile [dir]`` (or ``-Dshifu.profile=<dir>``) wraps
the step's process() in a device-timeline capture viewable in
TensorBoard/Perfetto — the TPU-native upgrade of the reference's
wall-clock log lines (``TrainModelProcessor.java:214``,
``DTWorker.java:687`` nano timers).  The always-on wall-clock spans live
in :mod:`shifu_tpu.obs.tracer`; this knob adds the compiled-op view when
asked.
"""

from __future__ import annotations

import logging
import os
from contextlib import nullcontext

log = logging.getLogger(__name__)


def profile_dir() -> str:
    """The configured capture root ('' = profiling off)."""
    from ..config import environment
    return environment.get_property("shifu.profile", "") or ""


def profile_step(step_name: str):
    """Context manager: a ``jax.profiler.trace`` capture under
    ``<profile_dir>/<step_name>`` when profiling is configured, else a
    free nullcontext."""
    trace_dir = profile_dir()
    if not trace_dir:
        return nullcontext()
    import jax
    out = os.path.join(os.path.abspath(trace_dir), step_name.lower())
    log.info("device trace -> %s (tensorboard --logdir or Perfetto)", out)
    from . import tracer
    tracer.event("profile_capture", step=step_name, dir=out)
    return jax.profiler.trace(out)
