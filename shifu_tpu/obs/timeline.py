"""Timeline export — span JSONL -> Chrome/Perfetto ``trace_event`` JSON.

``shifu-tpu analysis --telemetry --timeline out.json`` converts the
telemetry trace into the Trace Event Format that ``chrome://tracing``
and https://ui.perfetto.dev load directly: every span becomes a complete
(``"ph": "X"``) event with microsecond timestamps, every point event an
instant (``"ph": "i"``), one process per flush block (the step run's
pid), and — the part that makes the PR 2/6 ingest/compute overlap
visually auditable — INGEST-THREAD spans (``ingest.window_prep``, the
background prep/H2D pipeline) land on their own named track, separate
from the main thread's device-compute spans, so a starved accelerator
shows up as gaps on the compute track opposite solid bars on the ingest
track (the runtime-must-expose-timelines argument of the TF paper).

Track assignment: span records carry ``tid`` (the recording thread's
name, schema v5).  Any span recorded off the main thread — or named
``ingest.*`` (pre-v5 traces have no ``tid``) — routes to the ingest
track.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from ..ioutil import atomic_write_text
from . import tracer
from .report import load_blocks, trace_path

# fixed tids per process: compute first so it sorts on top in viewers
TID_MAIN = 1
TID_INGEST = 2
TRACK_NAMES = {TID_MAIN: "step / device compute",
               TID_INGEST: "ingest (window prep + H2D wait)"}


def _is_ingest(rec: Dict[str, Any]) -> bool:
    if str(rec.get("name", "")).startswith("ingest."):
        return True
    tid = rec.get("tid")
    return tid is not None and tid != "MainThread"


def _us(seconds: float) -> int:
    return int(round(float(seconds) * 1e6))


def to_trace_events(blocks: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Trace Event Format document (JSON-object flavour) for a parsed
    trace (see :func:`shifu_tpu.obs.report.load_blocks`)."""
    events: List[Dict[str, Any]] = []
    seen_pids: Dict[int, str] = {}
    for bi, block in enumerate(blocks):
        meta = block["meta"]
        pid = int(meta.get("pid") or (100000 + bi))
        step = meta.get("step") or "(unlabeled)"
        if pid not in seen_pids:
            seen_pids[pid] = step
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": f"shifu-tpu {step} "
                                              f"(pid {pid})"}})
            for tid, label in TRACK_NAMES.items():
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tid,
                               "args": {"name": label}})
                events.append({"ph": "M", "name": "thread_sort_index",
                               "pid": pid, "tid": tid,
                               "args": {"sort_index": tid}})
        for s in block["spans"]:
            events.append({
                "ph": "X", "name": s["name"], "cat": "span",
                "pid": pid,
                "tid": TID_INGEST if _is_ingest(s) else TID_MAIN,
                "ts": _us(s.get("ts") or 0.0),
                "dur": max(1, _us(s.get("dur_s") or 0.0)),
                "args": dict(s.get("attrs") or {}, span_id=s.get("id"),
                             parent=s.get("parent")),
            })
        for e in block["events"]:
            events.append({
                "ph": "i", "s": "t", "name": e["name"], "cat": "event",
                "pid": pid,
                "tid": TID_INGEST if _is_ingest(e) else TID_MAIN,
                "ts": _us(e.get("ts") or 0.0),
                "args": dict(e.get("attrs") or {}),
            })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "shifu-tpu telemetry",
            "schema_version": tracer.SCHEMA_VERSION,
            "steps": [b["meta"].get("step") for b in blocks],
        },
    }


def export_timeline(model_set_dir: str, out_path: str) -> Optional[str]:
    """Convert ``<modelset>/telemetry/trace.jsonl`` to ``out_path``.
    Returns the output path, or ``None`` (nothing written) when there is
    no telemetry to convert."""
    path = trace_path(model_set_dir)
    if not os.path.isfile(path):
        return None
    blocks = load_blocks(path)
    if not blocks:
        return None
    doc = to_trace_events(blocks)
    atomic_write_text(out_path, json.dumps(doc))
    return out_path
