"""Timeline export — span JSONL -> Chrome/Perfetto ``trace_event`` JSON.

``shifu-tpu analysis --telemetry --timeline out.json`` converts the
telemetry trace into the Trace Event Format that ``chrome://tracing``
and https://ui.perfetto.dev load directly: every span becomes a complete
(``"ph": "X"``) event with microsecond timestamps, every point event an
instant (``"ph": "i"``), one process per flush block (the step run's
pid), and — the part that makes the PR 2/6 ingest/compute overlap
visually auditable — INGEST-THREAD spans (``ingest.window_prep``, the
background prep/H2D pipeline) land on their own named track, separate
from the main thread's device-compute spans, so a starved accelerator
shows up as gaps on the compute track opposite solid bars on the ingest
track (the runtime-must-expose-timelines argument of the TF paper).

Track assignment: span records carry ``tid`` (the recording thread's
name, schema v5).  Sampled serving spans (``serve.request`` /
``serve.batch``, schema v8 — tid ``shifu-serve``) land on their own
``shifu-serve`` track so a request's queue-wait renders opposite the
batch launches that drained it; any other span recorded off the main
thread — or named ``ingest.*`` (pre-v5 traces have no ``tid``) —
routes to the ingest track.

Cross-process merge (:func:`export_merged_timeline`): N telemetry dirs
combine into ONE trace — every (dir, pid) pair becomes its own process
row (the per-proc tracks quorum/straggler analysis reads), and each
dir's span timestamps are normalized by its heartbeat-derived clock
offset (:func:`shifu_tpu.obs.monitor.dir_clock_offset`) so skewed host
clocks line up on a common axis.
"""

from __future__ import annotations

import json
import logging
import os
from typing import Any, Dict, List, Optional, Sequence

from ..ioutil import atomic_write_text
from . import tracer
from .report import load_blocks, trace_path

log = logging.getLogger(__name__)

# fixed tids per process: compute first so it sorts on top in viewers
TID_MAIN = 1
TID_INGEST = 2
TID_SERVE = 3
TRACK_NAMES = {TID_MAIN: "step / device compute",
               TID_INGEST: "ingest (window prep + H2D wait)",
               TID_SERVE: "shifu-serve (sampled request / batch spans)"}


def _is_serve(rec: Dict[str, Any]) -> bool:
    return (rec.get("tid") == "shifu-serve"
            or str(rec.get("name", "")).startswith("serve."))


def _is_ingest(rec: Dict[str, Any]) -> bool:
    if str(rec.get("name", "")).startswith("ingest."):
        return True
    tid = rec.get("tid")
    return tid is not None and tid != "MainThread"


def _tid_for(rec: Dict[str, Any]) -> int:
    if _is_serve(rec):
        return TID_SERVE
    return TID_INGEST if _is_ingest(rec) else TID_MAIN


def _us(seconds: float) -> int:
    return int(round(float(seconds) * 1e6))


def to_trace_events(blocks: List[Dict[str, Any]],
                    skipped: Optional[List[str]] = None) -> Dict[str, Any]:
    """Trace Event Format document (JSON-object flavour) for a parsed
    trace (see :func:`shifu_tpu.obs.report.load_blocks`).  Cost records
    (schema v6) annotate the output: every block's ROOT spans carry the
    block's total flops / bytes_accessed in ``args`` (Perfetto shows
    them in the span details pane) and each costed executable lands as
    an instant ``cost:<name>`` event with its per-signature numbers."""
    events: List[Dict[str, Any]] = []
    seen_pids: Dict[int, str] = {}
    for bi, block in enumerate(blocks):
        meta = block["meta"]
        pid = int(meta.get("pid") or (100000 + bi))
        step = meta.get("step") or "(unlabeled)"
        costs = block.get("costs") or []
        tot_flops = sum((c.get("flops") or 0.0)
                        * max(int(c.get("launches") or 0), 1)
                        for c in costs)
        tot_bytes = sum((c.get("bytes_accessed") or 0.0)
                        * max(int(c.get("launches") or 0), 1)
                        for c in costs)
        by_id = {s["id"]: s for s in block["spans"]}
        if pid not in seen_pids:
            seen_pids[pid] = step
            events.append({"ph": "M", "name": "process_name", "pid": pid,
                           "tid": 0, "args": {"name": f"shifu-tpu {step} "
                                              f"(pid {pid})"}})
            for tid, label in TRACK_NAMES.items():
                events.append({"ph": "M", "name": "thread_name",
                               "pid": pid, "tid": tid,
                               "args": {"name": label}})
                events.append({"ph": "M", "name": "thread_sort_index",
                               "pid": pid, "tid": tid,
                               "args": {"sort_index": tid}})
        for s in block["spans"]:
            args = dict(s.get("attrs") or {}, span_id=s.get("id"),
                        parent=s.get("parent"))
            if costs and s.get("parent") not in by_id:
                # root span: the block's cost totals, visible in the
                # span-details pane
                args["flops"] = tot_flops
                args["bytes_accessed"] = tot_bytes
            events.append({
                "ph": "X", "name": s["name"], "cat": "span",
                "pid": pid,
                "tid": _tid_for(s),
                "ts": _us(s.get("ts") or 0.0),
                "dur": max(1, _us(s.get("dur_s") or 0.0)),
                "args": args,
            })
        for c in costs:
            events.append({
                "ph": "i", "s": "t", "name": f"cost:{c.get('name')}",
                "cat": "cost", "pid": pid, "tid": TID_MAIN,
                "ts": _us(meta.get("ts") or 0.0),
                "args": {"signature": c.get("signature"),
                         "flops": c.get("flops"),
                         "bytes_accessed": c.get("bytes_accessed"),
                         "launches": c.get("launches"),
                         "compiles": c.get("compiles"),
                         "analytic": bool(c.get("analytic"))},
            })
        for e in block["events"]:
            events.append({
                "ph": "i", "s": "t", "name": e["name"], "cat": "event",
                "pid": pid,
                "tid": _tid_for(e),
                "ts": _us(e.get("ts") or 0.0),
                "args": dict(e.get("attrs") or {}),
            })
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": "shifu-tpu telemetry",
            "schema_version": tracer.SCHEMA_VERSION,
            "steps": [b["meta"].get("step") for b in blocks],
            # a crash mid-write tears the final trace line; the export
            # skips it like report.py does and SURFACES the count here
            "torn_lines_skipped": len(skipped or []),
        },
    }


def export_timeline(model_set_dir: str, out_path: str,
                    skipped: Optional[List[str]] = None) -> Optional[str]:
    """Convert ``<modelset>/telemetry/trace.jsonl`` to ``out_path``.
    Returns the output path, or ``None`` (nothing written) when there is
    no telemetry to convert.  Torn trace lines (crash mid-write) are
    skipped exactly like ``report.py`` skips them — logged, counted in
    the output's ``otherData.torn_lines_skipped``, and appended to
    ``skipped`` when the caller wants to surface them."""
    path = trace_path(model_set_dir)
    if not os.path.isfile(path):
        return None
    if skipped is None:
        skipped = []
    blocks = load_blocks(path, skipped=skipped)
    if not blocks:
        return None
    if skipped:
        log.warning("timeline export: %d torn trace line(s) skipped "
                    "(crashed run mid-write?) — the valid prefix was "
                    "exported", len(skipped))
    doc = to_trace_events(blocks, skipped=skipped)
    atomic_write_text(out_path, json.dumps(doc))
    return out_path


def export_merged_timeline(dirs: Sequence[str], out_path: str,
                           skipped: Optional[List[str]] = None
                           ) -> Optional[str]:
    """Merge N process telemetry dirs into ONE trace_event document (see
    module docs): per-(dir, pid) process rows, clock-offset-normalized
    timestamps, dir-labelled process names.  Returns the output path, or
    None when no dir holds a readable trace."""
    from .monitor import dir_clock_offset
    if skipped is None:
        skipped = []
    blocks: List[Dict[str, Any]] = []
    offsets: Dict[str, float] = {}
    pid_map: Dict[tuple, int] = {}
    for d in dirs:
        path = trace_path(d)
        if not os.path.isfile(path):
            continue
        off = dir_clock_offset(d)
        label = os.path.basename(os.path.abspath(d))
        offsets[label] = round(off, 3)
        for b in load_blocks(path, skipped=skipped):
            meta = b["meta"]
            key = (d, meta.get("pid"))
            # distinct pids per (dir, proc): two hosts can share a pid
            pid_map.setdefault(key, len(pid_map) + 1)
            meta["pid"] = pid_map[key]
            meta["step"] = f"{label}/{meta.get('step') or '?'}"
            if meta.get("ts"):
                meta["ts"] = float(meta["ts"]) - off
            for rec in b["spans"] + b["events"]:
                if rec.get("ts"):
                    rec["ts"] = float(rec["ts"]) - off
            blocks.append(b)
    if not blocks:
        return None
    doc = to_trace_events(blocks, skipped=skipped)
    doc["otherData"]["merged_dirs"] = [os.path.abspath(d) for d in dirs]
    doc["otherData"]["clock_offsets"] = offsets
    atomic_write_text(out_path, json.dumps(doc))
    return out_path
