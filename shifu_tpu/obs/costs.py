"""Device cost-attribution plane — FLOPs/bytes accounting per executable.

The obs plane's span tree (PR 1/7) says *where* wall-clock goes; this
module says *whether that time was well spent*: every named executable
records its XLA-estimated FLOPs and bytes accessed
(``lowered.cost_analysis()``), its compiled memory footprint
(``compiled.memory_analysis()``), and compile/launch counts, keyed by
``(name, abstract input shapes/dtypes)``.  The utilization report
(:mod:`obs.utilization`) joins these against fenced span wall times to
report achieved FLOP/s, bytes/s, percent-of-peak and a roofline verdict
per plane — the per-op cost visibility the TF system paper ties its
performance story to.

Three entry layers:

- :func:`costed_jit` — ``jax.jit`` replacement for a NAMED entry point.
  Dispatches through its own AOT cache (``lower()`` → ``compile()`` →
  call the compiled executable), so cost capture never double-compiles;
  any AOT oddity falls back to the plain jitted path per call, so the
  wrapper can slow a run down but never break it.  When telemetry is
  disabled at wrap time it returns the BARE ``jax.jit`` result — no
  wrapper frames, no registry writes.  ``lazy=True`` is the form for
  module-scope executables: the telemetry check moves to call time (one
  branch), because module import happens before the CLI's
  ``--telemetry`` flips the switch.
- :func:`record_executable` — the lower-level hook for code that
  already holds a ``(lowered, compiled)`` pair.
- :func:`register_cost_model` / :func:`record_model_launch` — analytic
  FLOP/byte models for Pallas kernels, which XLA's cost analysis cannot
  see through (a ``pallas_call`` is an opaque custom call); the hand
  models in :mod:`shifu_tpu.ops.hist_pallas` / :mod:`shifu_tpu.ops.tree`
  register here and land in the same registry.

THE SHAPE-CHURN SENTINEL: a second *distinct* signature under one name
bumps the ``xla.recompiles`` counter and logs a warn-once per name —
silent recompiles from shape churn are exactly the hazard the
padded-bucket serving plane must stay free of.

Cost records flush into the telemetry JSONL as ``{"kind": "cost", ...}``
lines (schema v6) alongside spans and metrics, so ``analysis
--telemetry --utilization`` can join them post-hoc.
"""

from __future__ import annotations

import inspect
import logging
import os
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from . import tracer

log = logging.getLogger(__name__)


# ------------------------------------------------------------ peak table
# Per-backend peak compute (bf16/matmul FLOP/s) and HBM bandwidth (B/s),
# matched by substring against jax's device_kind (lowercased).  Public
# spec-sheet numbers for the TPU generations; the CPU row is a
# placeholder order-of-magnitude so the report renders — override with
# SHIFU_TPU_PEAK_FLOPS / SHIFU_TPU_PEAK_BW on any rig you care about.
DEVICE_PEAKS: Tuple[Tuple[str, float, float], ...] = (
    ("tpu v6", 918e12, 1640e9),
    ("tpu v5p", 459e12, 2765e9),
    ("tpu v5 lite", 197e12, 819e9),
    ("tpu v5e", 197e12, 819e9),
    ("tpu v4", 275e12, 1228e9),
    ("tpu v3", 123e12, 900e9),
    ("tpu v2", 46e12, 700e9),
    ("cpu", 1e11, 5e10),
)
GENERIC_PEAKS = (1e11, 5e10)


def backend_info() -> Dict[str, str]:
    """(platform, device_kind) of local device 0 — stamped into the
    flush meta so a post-hoc report resolves the right peak row."""
    try:
        import jax
        d = jax.local_devices()[0]
        return {"platform": str(d.platform),
                "device_kind": str(d.device_kind)}
    except Exception:
        return {"platform": "unknown", "device_kind": "unknown"}


def resolve_peaks(backend: Optional[Dict[str, str]] = None
                  ) -> Tuple[float, float, str]:
    """(peak FLOP/s, peak B/s, provenance label).  Env overrides beat the
    table: ``SHIFU_TPU_PEAK_FLOPS`` / ``SHIFU_TPU_PEAK_BW`` (floats,
    per-device)."""
    backend = backend or backend_info()
    kind = str(backend.get("device_kind") or "").lower()
    platform = str(backend.get("platform") or "").lower()
    flops = bw = None
    label = "generic fallback"
    for sub, f, b in DEVICE_PEAKS:
        if sub in kind or sub == platform:
            flops, bw, label = f, b, sub
            break
    if flops is None:
        flops, bw = GENERIC_PEAKS
    for env, idx in (("SHIFU_TPU_PEAK_FLOPS", 0), ("SHIFU_TPU_PEAK_BW", 1)):
        v = os.environ.get(env)
        if v:
            try:
                if idx == 0:
                    flops = float(v)
                else:
                    bw = float(v)
                label += f" +{env}"
            except ValueError:
                log.warning("ignoring unparseable %s=%r", env, v)
    return flops, bw, label


# -------------------------------------------------------------- registry
class _Entry:
    """One (name, signature) executable's accumulated accounting."""

    __slots__ = ("name", "signature", "flops", "bytes_accessed", "memory",
                 "analytic", "compiles", "launches", "total_launches")

    def __init__(self, name: str, signature: str, flops: Optional[float],
                 bytes_accessed: Optional[float],
                 memory: Optional[Dict[str, int]], analytic: bool):
        self.name = name
        self.signature = signature
        self.flops = flops
        self.bytes_accessed = bytes_accessed
        self.memory = memory
        self.analytic = analytic
        self.compiles = 0          # since the last snapshot(reset=True)
        self.launches = 0          # since the last snapshot(reset=True)
        self.total_launches = 0    # process lifetime

    def to_record(self) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "kind": "cost", "name": self.name, "signature": self.signature,
            "flops": self.flops, "bytes_accessed": self.bytes_accessed,
            "compiles": self.compiles, "launches": self.launches,
            "analytic": self.analytic,
        }
        if self.memory is not None:
            rec["memory"] = self.memory
        return rec


class CostRegistry:
    """Process-wide executable cost table; thread-safe (the streamed
    window loop launches from the main thread while the heartbeat /
    exporter threads snapshot)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._entries: Dict[Tuple[str, Any], _Entry] = {}
        self._seen_sigs: Dict[str, set] = {}
        self._recompile_warned: set = set()

    def record(self, name: str, key: Any, signature: str,
               flops: Optional[float], bytes_accessed: Optional[float],
               memory: Optional[Dict[str, int]],
               analytic: bool = False) -> _Entry:
        """Register a freshly-built executable (one compile) under
        ``(name, key)`` and run the recompile sentinel."""
        recompiled = False
        with self._lock:
            ent = self._entries.get((name, key))
            if ent is None:
                ent = self._entries[(name, key)] = _Entry(
                    name, signature, flops, bytes_accessed, memory,
                    analytic)
            ent.compiles += 1
            sigs = self._seen_sigs.setdefault(name, set())
            if key not in sigs:
                if sigs:                       # a PRIOR different signature
                    recompiled = True
                sigs.add(key)
            warn = recompiled and name not in self._recompile_warned
            if warn:
                self._recompile_warned.add(name)
        if recompiled:
            from . import registry
            registry.counter("xla.recompiles").inc()
            if warn:
                # warn-once per name: the first shape-churn recompile is
                # the signal; per-occurrence logs would bury it
                log.warning(
                    "executable %r recompiled for a new input signature "
                    "%s — shape churn defeats the compile cache (pad/"
                    "bucket inputs to stable shapes); further recompiles "
                    "of this executable count in xla.recompiles silently",
                    name, signature)
        return ent

    def has_entry(self, name: str, key: Any) -> bool:
        with self._lock:
            return (name, key) in self._entries

    def launch(self, name: str, key: Any) -> None:
        with self._lock:
            ent = self._entries.get((name, key))
            if ent is None:
                return
            ent.launches += 1
            ent.total_launches += 1
        from . import registry
        registry.counter("xla.launches").inc()

    def snapshot(self, reset: bool = False) -> List[Dict[str, Any]]:
        """Cost records with activity since the last reset, stable-sorted
        by (name, signature) so the trace is diff-friendly."""
        with self._lock:
            ents = [e for _, e in sorted(self._entries.items(),
                                         key=lambda kv: (kv[1].name,
                                                         kv[1].signature))
                    if e.launches or e.compiles]
            recs = [e.to_record() for e in ents]
            if reset:
                for e in ents:
                    e.launches = 0
                    e.compiles = 0
        return recs

    def entries(self) -> List[_Entry]:
        with self._lock:
            return [e for _, e in sorted(self._entries.items(),
                                         key=lambda kv: (kv[1].name,
                                                         kv[1].signature))]

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()
            self._seen_sigs.clear()
            self._recompile_warned.clear()


_registry = CostRegistry()


def get_cost_registry() -> CostRegistry:
    return _registry


def cost_snapshot(reset: bool = False) -> List[Dict[str, Any]]:
    return _registry.snapshot(reset=reset)


def reset_for_tests() -> None:
    # the analytic-model table is NOT cleared: models register at kernel-
    # module import (like the metric manifest), not per run
    _registry.reset()


# ------------------------------------------------------------ signatures
def _leaf_sig(x: Any) -> str:
    """'f32[8,64]'-style abstract signature for one leaf (weak-typed
    python scalars keyed apart from committed arrays)."""
    import jax
    aval = jax.core.get_aval(x)
    try:
        aval = jax.core.raise_to_shaped(aval)
    except Exception:
        pass
    s = aval.str_short()
    if getattr(aval, "weak_type", False):
        s += "~"
    return s


def _split_static(fn: Callable, jit_kwargs: Dict[str, Any]
                  ) -> Tuple[set, set]:
    """(static positional indices, static kwarg names) a call must be
    partitioned by — mirrors how jax.jit resolves static_argnums /
    static_argnames against the wrapped function's signature."""
    nums = jit_kwargs.get("static_argnums") or ()
    if isinstance(nums, int):
        nums = (nums,)
    names = jit_kwargs.get("static_argnames") or ()
    if isinstance(names, str):
        names = (names,)
    idx = set(nums)
    try:
        params = list(inspect.signature(fn).parameters)
        for n in names:
            if n in params:
                idx.add(params.index(n))
    except (TypeError, ValueError):
        pass
    return idx, set(names)


def _signature(args: tuple, kwargs: dict, static_idx: set,
               static_names: set):
    """(hashable cache key, human signature string, dynamic args,
    dynamic kwargs, has_tracer) for one call."""
    import jax
    dyn_args = tuple(a for i, a in enumerate(args) if i not in static_idx)
    dyn_kwargs = {k: v for k, v in kwargs.items() if k not in static_names}
    statics = tuple(sorted(
        [(f"#{i}", repr(args[i])) for i in static_idx if i < len(args)]
        + [(k, repr(v)) for k, v in kwargs.items() if k in static_names]))
    leaves, treedef = jax.tree_util.tree_flatten((dyn_args, dyn_kwargs))
    has_tracer = any(isinstance(x, jax.core.Tracer) for x in leaves)
    if has_tracer:
        return None, "", dyn_args, dyn_kwargs, True
    sigs = tuple(_leaf_sig(x) for x in leaves)
    key = (treedef, sigs, statics)
    return key, ",".join(sigs), dyn_args, dyn_kwargs, False


def _cost_numbers(lowered) -> Tuple[Optional[float], Optional[float]]:
    """(flops, bytes accessed) from ``lowered.cost_analysis()`` — shapes
    vary by backend (dict / list-of-dict / None); absent keys are None,
    never a crash."""
    try:
        ca = lowered.cost_analysis()
    except Exception:
        return None, None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return None, None
    flops = ca.get("flops")
    bya = ca.get("bytes accessed")
    return (float(flops) if flops is not None else None,
            float(bya) if bya is not None else None)


def _memory_numbers(compiled) -> Optional[Dict[str, int]]:
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out: Dict[str, int] = {}
    for field, key in (("argument_size_in_bytes", "args"),
                       ("output_size_in_bytes", "out"),
                       ("temp_size_in_bytes", "temp"),
                       ("generated_code_size_in_bytes", "code")):
        v = getattr(ma, field, None)
        if v is not None:
            out[key] = int(v)
    return out or None


def record_executable(name: str, lowered, compiled,
                      signature: Optional[str] = None,
                      key: Optional[Any] = None) -> None:
    """Lower-level hook: register an already-built ``(lowered,
    compiled)`` pair under ``name``.  Derives the abstract input
    signature from the lowering when not supplied."""
    if signature is None:
        try:
            import jax
            avals = jax.tree_util.tree_leaves(lowered.in_avals)
            signature = ",".join(a.str_short() for a in avals)
        except Exception:
            signature = "unknown"
    flops, bya = _cost_numbers(lowered)
    _registry.record(name, key if key is not None else signature,
                     signature, flops, bya, _memory_numbers(compiled))


# ------------------------------------------------------------ costed_jit
class CostedJit:
    """A named, cost-attributed jitted callable (see module docs).

    Dispatch: per distinct ``(dynamic avals, static values)`` signature,
    ``lower()`` + ``compile()`` ONCE through jax's AOT path (cost and
    memory analyses come from exactly that lowering — no second compile)
    and launch the compiled executable directly afterwards.  Tracer
    inputs (the rare call from inside another trace) and any AOT
    failure fall through to the plain jitted path.
    """

    def __init__(self, name: str, fn: Callable, jit_kwargs: Dict[str, Any],
                 lazy: bool = False):
        import jax
        self.name = name
        self._jitted = jax.jit(fn, **jit_kwargs)
        self._static_idx, self._static_names = _split_static(fn, jit_kwargs)
        self._compiled: Dict[Any, Any] = {}
        self._lazy = lazy
        self._broken = False

    def __call__(self, *args, **kwargs):
        if self._broken or not tracer.enabled():
            return self._jitted(*args, **kwargs)
        try:
            key, sig, dyn_args, dyn_kwargs, has_tracer = _signature(
                args, kwargs, self._static_idx, self._static_names)
        except Exception:
            log.debug("costed_jit %r signature derivation failed; "
                      "falling back to plain jit", self.name, exc_info=True)
            self._broken = True
            return self._jitted(*args, **kwargs)
        if has_tracer:
            return self._jitted(*args, **kwargs)
        compiled = self._compiled.get(key)
        if compiled is None:
            try:
                lowered = self._jitted.lower(*args, **kwargs)
                compiled = lowered.compile()
            except Exception:
                log.debug("costed_jit %r AOT build failed; falling back "
                          "to plain jit", self.name, exc_info=True)
                self._broken = True
                return self._jitted(*args, **kwargs)
            flops, bya = _cost_numbers(lowered)
            _registry.record(self.name, key, sig, flops, bya,
                             _memory_numbers(compiled))
            self._compiled[key] = compiled
        _registry.launch(self.name, key)
        try:
            return compiled(*dyn_args, **dyn_kwargs)
        except Exception:
            # a dispatch-layer mismatch (committed-device or layout
            # corner) — the plain path is always correct
            log.debug("costed_jit %r AOT dispatch failed; using plain "
                      "jit for this call", self.name, exc_info=True)
            return self._jitted(*args, **kwargs)

    # parity with jax.jit's AOT surface, so call sites can still lower
    def lower(self, *args, **kwargs):
        return self._jitted.lower(*args, **kwargs)


def costed_jit(name: str, fn: Optional[Callable] = None, *,
               lazy: bool = False, **jit_kwargs):
    """``jax.jit`` with cost attribution under ``name`` (usable as
    ``costed_jit("plane.fn", fn, static_argnames=...)`` or as a
    decorator ``@costed_jit("plane.fn")``).

    Telemetry disabled at wrap time ⇒ returns the BARE ``jax.jit(fn)``
    — no wrapper frames, no registry writes, indistinguishable from
    un-instrumented code.  ``lazy=True`` defers the check to call time
    (one branch per call): required for module-scope executables, whose
    wrap runs at import, before ``--telemetry`` can flip the switch.
    """
    if fn is None:
        return lambda f: costed_jit(name, f, lazy=lazy, **jit_kwargs)
    if not lazy and not tracer.enabled():
        import jax
        return jax.jit(fn, **jit_kwargs)
    return CostedJit(name, fn, jit_kwargs, lazy=lazy)


# ------------------------------------------------------- analytic models
# Pallas kernels have no cost_analysis (XLA sees an opaque custom call):
# the kernel modules register small hand-derived FLOP/byte models here
# and the host launch loops record launches with the live shapes.
_models: Dict[str, Callable[..., Dict[str, float]]] = {}


def register_cost_model(name: str,
                        fn: Callable[..., Dict[str, float]]) -> None:
    """Register an analytic model: ``fn(**shape_kwargs)`` must return a
    dict with ``flops`` and ``bytes_accessed``."""
    _models[name] = fn


def cost_models() -> Dict[str, Callable[..., Dict[str, float]]]:
    return dict(_models)


def record_model_launch(name: str, **shape_kwargs) -> None:
    """Record one launch of an analytically-modeled kernel.  Entries key
    by the shape kwargs (the model's own signature space), count
    launches like compiled executables, and ride the same recompile
    sentinel.  No-op when telemetry is off or the model is unknown."""
    if not tracer.enabled():
        return
    model = _models.get(name)
    if model is None:
        log.debug("no cost model registered under %r", name)
        return
    key = tuple(sorted(shape_kwargs.items()))
    sig = ",".join(f"{k}={v}" for k, v in key)
    if not _registry.has_entry(name, key):
        try:
            est = model(**shape_kwargs)
        except Exception:
            log.debug("cost model %r failed for %r", name, shape_kwargs,
                      exc_info=True)
            return
        _registry.record(name, key, sig, float(est.get("flops") or 0.0),
                         float(est.get("bytes_accessed") or 0.0), None,
                         analytic=True)
    _registry.launch(name, key)
