"""Span tracer — nested wall-clock spans with a thread-safe collector.

The in-process analogue of the reference's per-step wall-clock log lines
and MR job counters: every pipeline step runs under a root span, phases
and trainer epochs nest inside it, and the whole trace lands as JSONL
under ``<modelset>/telemetry/`` for ``analysis --telemetry`` to render.

JSONL schema (``SCHEMA_VERSION``) — one JSON object per line, keyed by
``kind``:

- ``meta``:   ``{kind, schema_version, step, ts, pid}`` — opens a flush
  block (one per step run / bench flush);
- ``span``:   ``{kind, name, id, parent, ts, dur_s, attrs}`` — ``parent``
  is the enclosing span's ``id`` (``null`` for roots); ``ts`` is epoch
  seconds at entry; durations come from ``time.perf_counter``;
- ``event``:  ``{kind, name, ts, parent, attrs}`` — a point-in-time
  record (per-epoch trainer metrics, early stops, profile captures);
- ``metric``: one registry instrument snapshot (see
  :mod:`shifu_tpu.obs.registry`).

Zero-cost when disabled: :func:`span` returns a shared no-op singleton
(one function call + one branch per call site), :func:`event` returns
immediately, :func:`fence` never touches jax.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

# v2: ingest instrumentation (ingest.bytes_read / windows_emitted /
# h2d_wait_seconds / disk_passes / spill_hits / spill_misses counters;
# the report's "ingest stall fraction" line derives from them)
# v3: variable-selection plane instrumentation (varsel.host_syncs /
# mask_batches / windows counters, varsel.rows_per_sec / candidates
# gauges; bench varsel_* extras ride the same version)
# v4: disk-tail super-batch instrumentation (train.tail_sweeps /
# tail_repairs / tail_repair_levels counters; the report's tail-plane
# "tail sweeps" + ingest-stall lines and bench tail_* extras —
# disk_passes / bytes_read per tree, dual-schedule rates — derive
# from them)
# v5: observability plane v2 — span/event records carry ``tid`` (the
# recording thread's name: ingest-prep spans land on their own timeline
# track), live-span registry for heartbeats (obs/health), ingest.window_
# prep / ingest.h2d_wait spans, drift.* gauges (streaming PSI monitor),
# OpenMetrics snapshot names derive from the same registry records
# v6: device cost-attribution plane — ``{"kind": "cost"}`` records per
# named executable (flops / bytes_accessed / memory / compiles /
# launches, keyed by abstract input signature; obs/costs), the flush
# meta carries ``backend`` (platform + device_kind, resolving the peak
# table for the utilization report), xla.recompiles / xla.launches and
# ingest.rows_padded counters, timeline span args annotated with
# flops/bytes
# v7: online serving plane — serve.* instruments (requests / batches /
# rows_padded / flush_full / flush_deadline / request_errors / swaps
# counters, queue_depth / bucket_occupancy gauges, batch_latency_ms
# histogram) and the per-bucket ``serve.score.<key>.g<gen>.b<bucket>``
# cost records the AOT scorer registers (the recompile sentinel's
# serving beat)
# v8: request/SLO observability plane — sampled ``serve.request`` /
# ``serve.batch`` span records (tid ``shifu-serve``: per-request
# queue/deadline/pad/launch/device decomposition, batch spans linking
# member trace ids — the timeline's shifu-serve track), histogram
# metric records carry ``p50``/``p99`` (fixed-bin log sketch, also the
# metrics.prom quantile lines), ``slo.*`` gauges + the
# ``serve.trace_sampled`` counter, SERVE heartbeats may carry
# ``queue_depth`` / ``queue_buildup`` / ``slo`` extras, and monitor /
# timeline learn multi-dir (cross-process) aggregation
# v9: roofline speed round — ``serve.bucket_occupancy`` is a HISTOGRAM
# (was a last-batch gauge; p50/p99 quantile lines land in metrics.prom),
# ``serve.bucket_rungs_added`` counter (occupancy-driven ladder
# refinement), ``pallas.tree_traverse`` analytic cost records (the
# quantized uint8 traversal kernel is opaque to XLA cost analysis), and
# the bench emits ``nn_train_mixed_*`` / ``serve_quantized_*`` extras
# (mixed-precision ladder + quantized serving scorer)
# v10: elastic multi-controller plane — ``dcn.*`` instruments
# (connect_retries / steps_closed / step_timeouts / step_wait_seconds /
# late_applied / late_dropped / catchup_steps / rejoins counters,
# membership_epoch / live_members gauges), the ``dcn.step`` span, the
# monitor's ``quorum_lost`` summary field (aggregate + single-dir), and
# the bench's ``multihost_*`` extras (1→2→4 scaling + time-to-recover)
# v11: model-quality observability plane — sampled score-log segments
# under ``telemetry/scorelog/`` (``scorelog.*`` counters), the
# delayed-label join (``quality.outcomes`` / ``quality.outcomes_late``),
# the ``telemetry/posttrain.json`` training-time score snapshot eval
# persists, the ``telemetry/quality.json`` live-quality table
# (``quality.*`` gauges: per-generation live AUC / ECE / score-PSI),
# SERVE heartbeats may carry a ``quality`` extra, the refresh
# controller's third trigger source (``source: "quality"``), and the
# bench's ``--plane quality`` extras (``serve_scorelog_qps_frac`` +
# ``quality_label_flip_detect_s``, the lower-is-better ``*_detect_s``
# compare class)
# v12: raw-record serving + fleet — ``serve.raw_requests`` /
# ``serve.raw_rows`` / ``serve.raw_rejects`` counters (the fused
# transform's ingest beat: per-record coded rejection, never the
# batch), per-bucket ``serve.score.<key>.raw.b<bucket>`` cost records
# (the raw family of AOT executables under the same recompile
# sentinel), ``serve.fleet_replicas_up`` gauge + ``serve.fleet_drains``
# / ``serve.fleet_requeues`` / ``serve.fleet_swaps`` counters (the
# router's balancing/death/coordinated-swap beat), fleet worker
# heartbeats ride proc ``serve-<key>-<replica>``, and the bench's
# ``serve_raw_qps_frac`` + ``--plane fleet`` extras
# v13: overload protection — ``serve.shed_overload`` /
# ``serve.shed_expired`` / ``serve.cancelled`` counters (every shed is
# a coded fast-fail, never a silent drop), ``serve.mode`` gauge +
# ``serve.brownouts`` counter (brownout degradation, also a SERVE
# heartbeat ``mode`` extra and the monitor's ``<< BROWNOUT`` flag),
# ``serve.fleet_hedges`` / ``serve.fleet_breaker_opens`` /
# ``serve.fleet_retry_denied`` counters (the router's hedged-dispatch /
# circuit-breaker / retry-budget beat), SLO summaries carry a ``shed``
# total OUTSIDE availability burn, and the bench's ``--plane overload``
# extras (``serve_overload_goodput`` tracked via the new ``*_goodput``
# throughput suffix, ``serve_overload_p99_ms``, shed fractions)
# v14: one-parse offline pipeline — ``rawcache.hits`` / ``rawcache.
# misses`` / ``rawcache.bytes_written`` counters (the columnar raw-parse
# cache shared across stats/norm/eval), the ``ingest.parse_stall_frac``
# gauge (parse-pool consumer stall; the report's parse-stall line),
# ``ingest.disk_passes`` now also counts raw string-plane traversals
# (``DataSource.iter_chunks``) so the cold-vs-cached e2e delta is
# telemetry-backed, and the bench's ``--plane ingest`` extras
# (``stats_throughput`` / ``norm_throughput`` serial-vs-pooled) +
# ``pipeline_e2e_wall_s`` / ``pipeline_e2e_disk_passes`` on ``--plane
# e2e``
SCHEMA_VERSION = 14

_TRUE = ("1", "true", "on", "yes")

# tri-state enable: explicit set_enabled() override > cached env/property
# lookup.  The cache keeps enabled() at one global read + branch on the
# hot path; reset_for_tests()/set_enabled(None) clears it.
_enabled_override: Optional[bool] = None
_enabled_cache: Optional[bool] = None
_fence_cache: Optional[bool] = None


def _truthy(v: Optional[str]) -> bool:
    return v is not None and str(v).strip().lower() in _TRUE


def _lookup(env_key: str, *prop_keys: str) -> bool:
    v = os.environ.get(env_key)
    if v is None:
        from ..config import environment
        for k in prop_keys:
            v = environment.get_property(k)
            if v is not None:
                break
    return _truthy(v)


def enabled() -> bool:
    """Is telemetry on?  env ``SHIFU_TPU_TELEMETRY`` / property
    ``shifu.telemetry`` / :func:`set_enabled` (CLI ``--telemetry``)."""
    if _enabled_override is not None:
        return _enabled_override
    global _enabled_cache
    if _enabled_cache is None:
        _enabled_cache = _lookup("SHIFU_TPU_TELEMETRY",
                                 "shifu.telemetry", "shifu.tpu.telemetry")
    return _enabled_cache


def set_enabled(value: Optional[bool]) -> None:
    """Programmatic override (CLI flag, tests); ``None`` restores the
    env/property lookup."""
    global _enabled_override, _enabled_cache, _fence_cache
    _enabled_override = value
    _enabled_cache = None
    _fence_cache = None


def fencing_enabled() -> bool:
    """Fenced spans: ``jax.block_until_ready`` at :meth:`Span.fence` so a
    span's wall-clock covers the device work it launched, not just the
    dispatch.  Env ``SHIFU_TPU_TELEMETRY_FENCE`` / property
    ``shifu.telemetry.fence``; only active while telemetry is on."""
    global _fence_cache
    if not enabled():
        return False
    if _fence_cache is None:
        _fence_cache = _lookup("SHIFU_TPU_TELEMETRY_FENCE",
                               "shifu.telemetry.fence")
    return _fence_cache


# ------------------------------------------------------------- collector
class _Collector:
    """Thread-safe record buffer + per-thread span stack."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._records: List[Dict[str, Any]] = []
        self._tls = threading.local()
        self._next_id = 0
        # id -> (name, thread name, entry ts) for spans currently OPEN —
        # the heartbeat thread (obs/health) reads this to report what
        # each thread is doing *right now*, between record flushes
        self._live: Dict[int, tuple] = {}

    def new_id(self) -> int:
        with self._lock:
            self._next_id += 1
            return self._next_id

    @property
    def stack(self) -> List[int]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def current_parent(self) -> Optional[int]:
        st = self.stack
        return st[-1] if st else None

    def add(self, rec: Dict[str, Any]) -> None:
        with self._lock:
            self._records.append(rec)

    def span_opened(self, span_id: int, name: str, ts: float) -> None:
        with self._lock:
            self._live[span_id] = (name, threading.current_thread().name,
                                   ts)

    def span_closed(self, span_id: int) -> None:
        with self._lock:
            self._live.pop(span_id, None)

    def live_spans(self) -> List[Dict[str, Any]]:
        """Currently-open spans, oldest first (heartbeat surface)."""
        with self._lock:
            return [{"id": i, "name": n, "thread": t, "ts": ts}
                    for i, (n, t, ts) in sorted(self._live.items())]

    def drain(self) -> List[Dict[str, Any]]:
        with self._lock:
            out, self._records = self._records, []
            return out

    def peek(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self._live.clear()
        self._tls = threading.local()


_collector = _Collector()


class Span:
    """A live span; use via ``with span("name", k=v) as sp:``.  Extra
    attributes attach with :meth:`set`; :meth:`fence` blocks on device
    values when fencing is on so the duration covers real work."""

    __slots__ = ("name", "attrs", "id", "parent", "_ts", "_t0")

    def __init__(self, name: str, attrs: Dict[str, Any]):
        self.name = name
        self.attrs = attrs
        self.id = _collector.new_id()
        self.parent: Optional[int] = None
        self._ts = 0.0
        self._t0 = 0.0

    def __enter__(self) -> "Span":
        self.parent = _collector.current_parent()
        _collector.stack.append(self.id)
        self._ts = time.time()
        self._t0 = time.perf_counter()
        _collector.span_opened(self.id, self.name, self._ts)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.perf_counter() - self._t0
        st = _collector.stack
        if st and st[-1] == self.id:
            st.pop()
        _collector.span_closed(self.id)
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        _collector.add({"kind": "span", "name": self.name, "id": self.id,
                        "parent": self.parent, "ts": round(self._ts, 3),
                        "dur_s": round(dur, 6),
                        "tid": threading.current_thread().name,
                        "attrs": self.attrs})
        return False

    def set(self, **attrs: Any) -> "Span":
        self.attrs.update(attrs)
        return self

    def fence(self, value: Any) -> Any:
        """Block until ``value``'s device buffers are ready (fencing mode
        only) so async dispatch doesn't flatter this span; returns the
        value either way."""
        if fencing_enabled():
            import jax
            jax.block_until_ready(value)
        return value


class _NullSpan:
    """Shared no-op span: the disabled path allocates nothing."""

    __slots__ = ()
    id = None
    parent = None

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    def set(self, **attrs: Any) -> "_NullSpan":
        return self

    def fence(self, value: Any) -> Any:
        return value


_NULL_SPAN = _NullSpan()


def span(name: str, /, **attrs: Any):
    """Open a (nested) span.  No-op singleton when telemetry is off."""
    if not enabled():
        return _NULL_SPAN
    return Span(name, attrs)


def event(name: str, /, **attrs: Any) -> None:
    """Record a point-in-time event under the current span (per-epoch
    trainer metrics, early stops, ...)."""
    if not enabled():
        return
    _collector.add({"kind": "event", "name": name,
                    "ts": round(time.time(), 3),
                    "parent": _collector.current_parent(),
                    "tid": threading.current_thread().name, "attrs": attrs})


def record_span(name: str, ts: float, dur_s: float,
                attrs: Optional[Dict[str, Any]] = None,
                tid: Optional[str] = None,
                parent: Optional[int] = None) -> Optional[int]:
    """Record an externally-timed span.  Producers whose spans start and
    end on DIFFERENT threads (the serve plane: a request enters on the
    caller's thread and completes on the batcher worker) measure with
    their own perf counters and emit the finished span here; ``tid``
    overrides the track label (e.g. ``shifu-serve``).  Returns the span
    id, or None (no allocation) when telemetry is off."""
    if not enabled():
        return None
    sid = _collector.new_id()
    _collector.add({"kind": "span", "name": name, "id": sid,
                    "parent": parent, "ts": round(float(ts), 6),
                    "dur_s": round(float(dur_s), 6),
                    "tid": tid or threading.current_thread().name,
                    "attrs": dict(attrs or {})})
    return sid


def fence(value: Any) -> Any:
    """Module-level fence for call sites without a span handle."""
    if fencing_enabled():
        import jax
        jax.block_until_ready(value)
    return value


def pending_records() -> List[Dict[str, Any]]:
    """Snapshot of not-yet-flushed records (tests, bench)."""
    return _collector.peek()


def live_spans() -> List[Dict[str, Any]]:
    """Spans currently open across ALL threads (the heartbeat's 'what is
    this process doing right now' surface).  Empty when disabled."""
    if not enabled():
        return []
    return _collector.live_spans()


def flush(path: str, step: Optional[str] = None,
          extra_meta: Optional[Dict[str, Any]] = None) -> bool:
    """Append the buffered spans/events plus a registry snapshot to
    ``path`` as one JSONL block opened by a ``meta`` line, then clear
    both.  Returns False (and writes nothing) when telemetry is off."""
    if not enabled():
        return False
    from . import costs, registry
    records = _collector.drain()
    metrics = registry.snapshot(reset=True)
    cost_recs = costs.cost_snapshot(reset=True)
    meta: Dict[str, Any] = {"kind": "meta", "schema_version": SCHEMA_VERSION,
                            "step": step, "ts": round(time.time(), 3),
                            "pid": os.getpid(),
                            "backend": costs.backend_info()}
    if extra_meta:
        meta.update(extra_meta)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    # append-only trace sink BY DESIGN: each flush appends a block;
    # every reader (report/timeline) skips a torn final line
    with open(path, "a") as f:  # shifu-lint: disable=atomic-write
        for rec in [meta] + records + metrics + cost_recs:
            f.write(json.dumps(rec) + "\n")
    return True


def reset_for_tests() -> None:
    from . import costs
    from .registry import get_registry
    set_enabled(None)
    _collector.clear()
    get_registry().reset()
    costs.reset_for_tests()
