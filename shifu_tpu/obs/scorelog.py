"""Sampled prediction logging from the serve path — the quality feed.

The reference's ``posttrain`` step computes score-distribution stats
once, offline, from the eval run; production then flies blind.  This
module is the live half of that loop: the micro-batcher taps every
completed launch and, for the head-sampled fraction of requests, appends
one JSON record per request — timestamp, serving model generation,
request id, scores, and (when present) the sampled bin vector — into
bounded append-only segments under ``<modelset>/telemetry/scorelog/``.

Crash-safety contract (the torn-trace-line contract, at segment
granularity): the active segment is written as ``seg-NNNNNN.jsonl.open``
and COMMITTED by an atomic ``os.replace`` to ``seg-NNNNNN.jsonl`` at
rotation.  A crash mid-segment leaves a ``.open`` orphan: readers skip
it with a surfaced count, committed segments are untouched, and the next
writer sweeps the orphan and continues at the next index.  A disk budget
(``-Dshifu.scorelog.budgetBytes``) prunes the OLDEST committed segments
so the log can run unattended.

Zero-cost when off (the default): ``-Dshifu.scorelog.sampleRate`` is 0,
the server constructs no :class:`ScoreLog`, and the batcher's tap is one
``is not None`` check per launch.  Sampling itself is head-sampling —
one RNG draw per scored request, before any formatting.

Single-writer by design: one serve process owns a model set's score log
(the same assumption the heartbeat and journal planes make).
"""

from __future__ import annotations

import json
import logging
import os
import random
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .. import faults
from . import registry

log = logging.getLogger(__name__)

SEGMENT_PREFIX = "seg-"
SEGMENT_SUFFIX = ".jsonl"
OPEN_SUFFIX = ".open"

DEFAULT_SEGMENT_BYTES = 1 << 20          # 1 MiB per committed segment
DEFAULT_BUDGET_BYTES = 64 << 20          # 64 MiB total, oldest pruned


def scorelog_dir(model_set_dir: str) -> str:
    return os.path.join(model_set_dir, "telemetry", "scorelog")


def _float_knob(name: str, override, default: float) -> float:
    if override is not None:
        return float(override)
    from ..config import environment
    p = environment.get_property(name)
    if p is not None:
        try:
            return float(p)
        except (TypeError, ValueError):
            pass
    return default


def scorelog_sample_rate(override: Optional[float] = None) -> float:
    """``-Dshifu.scorelog.sampleRate`` (0..1, default 0 = the whole
    quality plane off)."""
    return min(max(_float_knob("shifu.scorelog.sampleRate", override,
                               0.0), 0.0), 1.0)


def scorelog_segment_bytes(override: Optional[int] = None) -> int:
    """``-Dshifu.scorelog.segmentBytes`` — bytes per segment before
    atomic rotation."""
    return max(int(_float_knob("shifu.scorelog.segmentBytes", override,
                               DEFAULT_SEGMENT_BYTES)), 1)


def scorelog_budget_bytes(override: Optional[int] = None) -> int:
    """``-Dshifu.scorelog.budgetBytes`` — total committed-segment disk
    budget; oldest segments pruned past it."""
    return max(int(_float_knob("shifu.scorelog.budgetBytes", override,
                               DEFAULT_BUDGET_BYTES)), 1)


class ScoreLog:
    """Bounded append-only score log with atomic segment rotation.

    ``gen_fn`` supplies the CURRENT serving generation at log time (the
    registry's swap counter), so records written across a hot-swap are
    attributed to the model that actually scored them.  ``on_log`` is
    the in-process fast path to the join/quality plane — called with
    ``(req, scores, gen, ts)`` for every sampled record, so the quality
    monitor never re-reads its own segments.
    """

    def __init__(self, root: str, sample_rate: Optional[float] = None,
                 segment_bytes: Optional[int] = None,
                 budget_bytes: Optional[int] = None,
                 gen_fn: Optional[Callable[[], int]] = None,
                 on_log: Optional[Callable] = None,
                 clock: Callable[[], float] = time.time):
        self.root = root
        self.sample_rate = scorelog_sample_rate(sample_rate)
        self.segment_bytes = scorelog_segment_bytes(segment_bytes)
        self.budget_bytes = scorelog_budget_bytes(budget_bytes)
        self._gen_fn = gen_fn
        self._on_log = on_log
        self._clock = clock
        self._rng = random.Random(0x5C02E)
        self.stats: Dict[str, int] = {"records": 0, "segments": 0,
                                      "pruned": 0, "write_errors": 0}
        os.makedirs(self.root, exist_ok=True)
        self.recovered = self._sweep_orphans()
        self._seq = self._next_seq()
        self._file = None
        self._path = None
        self._bytes = 0

    # ------------------------------------------------------------ recovery
    def _sweep_orphans(self) -> int:
        """A ``.open`` segment on startup is a previous writer's torn
        final segment (killed mid-write or mid-rotation): drop it —
        committed segments carry the durable history."""
        n = 0
        for name in os.listdir(self.root):
            if name.endswith(OPEN_SUFFIX):
                try:
                    os.remove(os.path.join(self.root, name))
                    n += 1
                except OSError:         # pragma: no cover
                    log.warning("scorelog orphan sweep failed",
                                exc_info=True)
        return n

    def _next_seq(self) -> int:
        seqs = [int(n[len(SEGMENT_PREFIX):-len(SEGMENT_SUFFIX)])
                for n in os.listdir(self.root)
                if n.startswith(SEGMENT_PREFIX)
                and n.endswith(SEGMENT_SUFFIX)]
        return max(seqs) + 1 if seqs else 0

    # ------------------------------------------------------------- logging
    def log(self, req_id: Optional[str], scores,
            bins=None, gen: Optional[int] = None,
            ts: Optional[float] = None) -> Optional[str]:
        """Head-sampled append of one scored request; returns the
        request id when the record was sampled, else ``None``."""
        if self._rng.random() >= self.sample_rate:
            return None
        req = req_id if req_id is not None else os.urandom(8).hex()
        if gen is None:
            gen = int(self._gen_fn()) if self._gen_fn is not None else 0
        if ts is None:
            ts = self._clock()
        scores = np.asarray(scores, np.float32).ravel()
        rec: Dict[str, Any] = {
            "ts": round(float(ts), 3), "gen": int(gen), "req": req,
            "scores": [round(float(s), 6) for s in scores]}
        if bins is not None:
            rec["bins"] = np.asarray(bins).astype(int).tolist()
        try:
            self._append(json.dumps(rec, sort_keys=True) + "\n")
        except OSError:
            self.stats["write_errors"] += 1
            log.warning("scorelog append failed", exc_info=True)
        self.stats["records"] += 1
        registry.counter("scorelog.records").inc()
        if self._on_log is not None:
            self._on_log(req, scores, int(gen), float(ts))
        return req

    def _append(self, line: str) -> None:
        if self._file is None:
            self._path = os.path.join(
                self.root,
                f"{SEGMENT_PREFIX}{self._seq:06d}{SEGMENT_SUFFIX}"
                f"{OPEN_SUFFIX}")
            # the .open suffix IS the torn marker; commit is the atomic
            # rename at rotation
            self._file = open(self._path, "a")  # shifu-lint: disable=atomic-write
            self._bytes = 0
        self._file.write(line)
        self._file.flush()
        self._bytes += len(line)
        if self._bytes >= self.segment_bytes:
            self._rotate()

    def _rotate(self) -> None:
        """Commit the active segment: fsync + atomic rename drops the
        ``.open`` torn marker in one step."""
        f, path = self._file, self._path
        self._file = None
        f.flush()
        os.fsync(f.fileno())
        f.close()
        faults.fire("obs", "scorelog", self._seq, path=path)
        os.replace(path, path[:-len(OPEN_SUFFIX)])
        self._seq += 1
        self._bytes = 0
        self.stats["segments"] += 1
        registry.counter("scorelog.segments").inc()
        self._enforce_budget()

    def _enforce_budget(self) -> None:
        names = sorted(n for n in os.listdir(self.root)
                       if n.startswith(SEGMENT_PREFIX)
                       and n.endswith(SEGMENT_SUFFIX))
        sizes = {}
        for n in names:
            try:
                sizes[n] = os.path.getsize(os.path.join(self.root, n))
            except OSError:             # pragma: no cover
                sizes[n] = 0
        total = sum(sizes.values())
        pruned = 0
        for n in names[:-1]:            # never prune the newest segment
            if total <= self.budget_bytes:
                break
            try:
                os.remove(os.path.join(self.root, n))
            except OSError:             # pragma: no cover
                continue
            total -= sizes[n]
            pruned += 1
        if pruned:
            self.stats["pruned"] += pruned
            registry.counter("scorelog.pruned_segments").inc(pruned)

    def close(self) -> None:
        """Clean shutdown commits the partial tail segment (only a
        CRASH leaves a torn ``.open``)."""
        if self._file is not None and self._bytes > 0:
            try:
                self._rotate()
            except OSError:             # pragma: no cover
                log.warning("scorelog close rotation failed",
                            exc_info=True)
        elif self._file is not None:
            self._file.close()
            self._file = None


def read_score_records(root: str,
                       skipped: Optional[List[str]] = None
                       ) -> List[Dict[str, Any]]:
    """Every record in COMMITTED segments, oldest first.  Uncommitted
    ``.open`` segments (a crashed writer's torn tail) and torn JSON
    lines are skipped with their names appended to ``skipped`` — the
    torn-trace-line contract."""
    recs: List[Dict[str, Any]] = []
    if not os.path.isdir(root):
        return recs
    for name in sorted(os.listdir(root)):
        if name.endswith(OPEN_SUFFIX):
            if skipped is not None:
                skipped.append(name)
            continue
        if not (name.startswith(SEGMENT_PREFIX)
                and name.endswith(SEGMENT_SUFFIX)):
            continue
        with open(os.path.join(root, name)) as f:
            for i, line in enumerate(f):
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    if skipped is not None:
                        skipped.append(f"{name}:{i + 1}")
    return recs
