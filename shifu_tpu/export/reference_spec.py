"""Writers for the reference's serialized model formats.

The mirror of :mod:`shifu_tpu.models.reference_import`: emit trained models
in the byte formats the reference's dependency-free Java consumers load in
production —

- ``model*.nn``: Encog 3.0 EG text (``PersistBasicFloatNetwork`` layout,
  the format ``core/alg/NNTrainer.java`` persists and the reference's
  bundled example models ship in, e.g.
  ``src/test/resources/model/model0.nn``);
- ``model*.gbt`` / ``model*.rf``: gzipped ``BinaryDTSerializer`` version-4
  forests (``core/dtrain/dt/BinaryDTSerializer.java:60-160``), loadable by
  ``dt/IndependentTreeModel.java:887-1075`` and ``shifu convert``.

Round-trip oracle: ``models/reference_import.py`` re-reads both formats, and
``tests/test_reference_export.py`` pins write → re-read score parity.

Semantics note (inherent format difference, not a bug): our trees route a
MISSING numeric value through its own bin, while the reference format can
only impute missing to the column mean before walking
(``IndependentTreeModel.predictNode`` line 524).  Exported trees therefore
score identically on rows whose numeric values are present; rows with
missing numerics follow the reference's mean-imputation path.  Categorical
missing is exact either way (the reference's missing bucket
``index == categoricalSize`` maps 1:1 onto our missing bin).
"""

from __future__ import annotations

import gzip
import io
import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

from .. import ioutil
from ..config.errors import ErrorCode, ShifuError
from ..models.nn import NNModelSpec
from ..models.tree import TreeModelSpec
from ..ops.tree import TreeArrays

# ----------------------------------------------------------- Encog EG (.nn)

_EG_ACT_NAMES = {
    "sigmoid": "ActivationSigmoid",
    "tanh": "ActivationTANH",
    "linear": "ActivationLinear",
    "relu": "ActivationReLU",
    "log": "ActivationLOG",
    "sin": "ActivationSIN",
}


def _eg_float(x: float) -> str:
    """Java ``Double.toString``-ish rendering: repr keeps round-trip
    precision; Encog's CSVFormat parses plain decimal/scientific forms."""
    return repr(float(x))


def write_encog_nn(path: str, spec: NNModelSpec, params: List[Dict]) -> None:
    """Write our NN params as an Encog 3.0 EG BasicNetwork text file.

    Layout (mirrors the reference's persisted models, e.g.
    ``src/test/resources/model/model0.nn``): layers stored OUTPUT-FIRST;
    ``layerCounts`` include one bias neuron everywhere but the output
    layer; each weight block is ``[feedCounts[L-1], layerCounts[L]]``
    row-major with the bias column last.  ``models.reference_import.
    load_encog_nn`` is the round-trip reader.
    """
    acts = [a.lower() for a in spec.activations]
    bad = [a for a in set(acts + [spec.output_activation.lower()])
           if a not in _EG_ACT_NAMES]
    if bad:
        raise ShifuError(ErrorCode.ERROR_UNSUPPORT_ALG,
                         f"activation(s) {bad} have no Encog equivalent — "
                         "EG export supports sigmoid/tanh/linear/relu/log/sin")
    # output-first structural arrays
    feed = [spec.output_dim] + list(reversed(spec.hidden_nodes)) \
        + [spec.input_dim]
    n_layers = len(feed)
    counts = [feed[0]] + [f + 1 for f in feed[1:]]       # bias everywhere
    bias_act = [0.0] + [1.0] * (n_layers - 1)            # but the output
    layer_index = [0]
    for c in counts[:-1]:
        layer_index.append(layer_index[-1] + c)
    # weight blocks output-first: block L-1 reads layer L (incl. bias)
    blocks: List[np.ndarray] = []
    for layer in range(1, n_layers):
        p = params[n_layers - 1 - layer]                 # params input-first
        w = np.asarray(p["w"], np.float64)               # [in, out]
        b = np.asarray(p["b"], np.float64)               # [out]
        blocks.append(np.concatenate([w.T, b[:, None]], axis=1))
    weights = np.concatenate([blk.reshape(-1) for blk in blocks])
    w_index = [0]
    for blk in blocks:
        w_index.append(w_index[-1] + blk.size)
    # layerOutput: bias neurons emit their biasActivation, others 0
    output = []
    for li, c in enumerate(counts):
        output.extend([0.0] * feed[li] + [1.0] * (c - feed[li]))
    act_names = [_EG_ACT_NAMES[spec.output_activation.lower()]] \
        + [_EG_ACT_NAMES[a] for a in reversed(acts)] \
        + [_EG_ACT_NAMES["linear"]]                      # input layer

    def ints(v):
        return ",".join(str(int(x)) for x in v)

    lines = [
        "encog,BasicNetwork,java,3.0.0,1,0",
        "[BASIC]",
        "[BASIC:PARAMS]",
        "[BASIC:NETWORK]",
        "beginTraining=0",
        "connectionLimit=0",
        "contextTargetOffset=" + ints([0] * n_layers),
        "contextTargetSize=" + ints([0] * n_layers),
        f"endTraining={n_layers - 1}",
        "hasContext=f",
        f"inputCount={spec.input_dim}",
        "layerCounts=" + ints(counts),
        "layerFeedCounts=" + ints(feed),
        "layerContextCount=" + ints([0] * n_layers),
        "layerIndex=" + ints(layer_index),
        "output=" + ",".join(_eg_float(x) if x else "0" for x in output),
        f"outputCount={spec.output_dim}",
        "weightIndex=" + ints(w_index),
        "weights=" + ",".join(_eg_float(x) for x in weights),
        "biasActivation=" + ",".join("1" if b else "0" for b in bias_act),
        "[BASIC:ACTIVATION]",
    ] + [f'"{n}"' for n in act_names]
    ioutil.atomic_write_text(path, "\n".join(lines) + "\n")


# ------------------------------------------- BinaryDTSerializer (.gbt/.rf)

class _JavaDataOutput:
    """DataOutput writer for the subset BinaryDTSerializer emits."""

    def __init__(self):
        self._b = io.BytesIO()

    def write_int(self, v: int) -> None:
        self._b.write(struct.pack(">i", int(v)))

    def write_short(self, v: int) -> None:
        self._b.write(struct.pack(">h", int(v)))

    def write_byte(self, v: int) -> None:
        self._b.write(struct.pack(">b", int(v)))

    def write_boolean(self, v: bool) -> None:
        self._b.write(b"\x01" if v else b"\x00")

    def write_double(self, v: float) -> None:
        self._b.write(struct.pack(">d", float(v)))

    def write_float(self, v: float) -> None:
        self._b.write(struct.pack(">f", float(v)))

    def write_utf(self, s: str) -> None:
        data = s.encode("utf-8")
        self._b.write(struct.pack(">H", len(data)))
        self._b.write(data)

    def write_category(self, s: str, max_len: int = 10000) -> None:
        """``BinaryDTSerializer`` category entry: plain writeUTF below the
        reference's ``MAX_CATEGORICAL_VAL_LEN``, else the -1 short marker +
        int length + raw bytes (the 16k writeUTF limit workaround)."""
        if len(s) < max_len:
            self.write_utf(s)
        else:
            data = s.encode("utf-8")
            self.write_short(-1)
            self.write_int(len(data))
            self._b.write(data)

    def getvalue(self) -> bytes:
        return self._b.getvalue()


def _write_bitset(d: _JavaDataOutput, cats: Sequence[int],
                  n_categories: int) -> None:
    """``SimpleBitSet.write``: byte-word count then words, bit ``i%8`` of
    word ``i/8`` = category index ``i`` (sized like the Java side: one
    spare slot past the category count, the missing bucket)."""
    n_words = (n_categories + 1 + 7) // 8 + 1
    words = bytearray(n_words)
    for c in cats:
        words[c // 8] |= (1 << (c % 8))
    d.write_int(n_words)
    for w in words:
        d.write_byte(w if w < 128 else w - 256)


def _write_node(d: _JavaDataOutput, trees_idx: int, spec: TreeModelSpec,
                tree: TreeArrays, i: int, col_info: Dict[int, dict]) -> None:
    """Recursive ``Node.write`` (``dt/Node.java:583-624``): array slot
    ``i`` maps to the reference's heap node id ``i + 1`` (root=1, left of
    id j = 2j, right = 2j+1 — exactly our complete-array children
    2i+1/2i+2)."""
    total = len(tree.split_feat)
    sf = int(tree.split_feat[i])
    is_leaf = sf < 0 or (2 * i + 2) >= total
    d.write_int(i + 1)                                   # node id
    d.write_float(0.0)                                   # gain (not stored)
    d.write_double(0.0)                                  # wgtCnt (not stored)
    if is_leaf:
        d.write_boolean(False)                           # no split
    else:
        info = col_info[sf]
        d.write_boolean(True)
        d.write_int(info["column_num"])                  # Split.write
        lm = np.asarray(tree.left_mask[i])
        if info["categories"] is not None:
            cats = info["categories"]
            nb = len(cats)
            d.write_byte(2)                              # CATEGORICAL
            left_cats = [b for b in range(nb) if lm[b]]
            if nb < len(lm) and lm[nb]:
                # missing bin goes LEFT: the format routes missing to the
                # non-bitset side, so store the RIGHT categories instead
                d.write_boolean(False)                   # isLeft = False
                right_cats = [b for b in range(nb) if not lm[b]]
                d.write_boolean(False)                   # categories != null
                _write_bitset(d, right_cats, nb)
            else:
                d.write_boolean(True)                    # isLeft = True
                d.write_boolean(False)                   # categories != null
                _write_bitset(d, left_cats, nb)
        else:
            d.write_byte(1)                              # CONTINUOUS
            bnd = info["boundaries"]
            nb = len(bnd)
            ks = [b for b in range(min(nb, len(lm))) if lm[b]]
            k = max(ks) if ks else -1
            # left bins 0..k ⟺ value < boundaries[k+1] (bin b spans
            # [bnd[b], bnd[b+1]) with bnd[0] = -inf)
            if k < 0:
                thr = float(bnd[0]) if nb else float("-inf")
            elif k + 1 < nb:
                thr = float(bnd[k + 1])
            else:
                thr = float("inf")                       # every value left
            d.write_double(thr)
    d.write_boolean(is_leaf)                             # isRealLeaf
    if is_leaf:
        d.write_boolean(True)                            # predict != null
        lv = np.asarray(tree.leaf_value[i])
        d.write_double(float(lv))                        # Predict.write
        d.write_byte(0)                                  # classValue
        d.write_boolean(False)                           # no left child
        d.write_boolean(False)                           # no right child
    else:
        d.write_boolean(True)
        _write_node(d, trees_idx, spec, tree, 2 * i + 1, col_info)
        d.write_boolean(True)
        _write_node(d, trees_idx, spec, tree, 2 * i + 2, col_info)


def _leaf_only_tree(predict: float) -> TreeArrays:
    """A root-leaf tree carrying a constant — the GBT prior ``f_0``
    becomes tree 0 with learningRate 1 (the format has no init slot)."""
    return TreeArrays(split_feat=np.full(1, -1, np.int32),
                      left_mask=np.zeros((1, 1), bool),
                      leaf_value=np.asarray([predict], np.float32), depth=0)


def write_reference_tree(path: str, spec: TreeModelSpec,
                         trees: List[TreeArrays], column_configs,
                         bags: Optional[List[List[TreeArrays]]] = None) -> None:
    """Write a forest as a gzipped ``BinaryDTSerializer`` version-4 stream
    (``BinaryDTSerializer.java:60-160``), loadable by the reference's
    ``IndependentTreeModel`` and by ``models.reference_import.
    load_reference_tree`` (the round-trip oracle).

    ``spec.column_nums[j]`` maps dense feature ``j`` to its columnNum;
    boundaries/categories come from the matching ColumnConfig (exactly the
    maps the Java writer takes from its ColumnConfig list).
    """
    if (spec.extra or {}).get("n_classes", 0) > 2:
        raise ShifuError(
            ErrorCode.ERROR_UNSUPPORT_ALG,
            "NATIVE multiclass forests have no BinaryDTSerializer layout "
            "(the reference trains multiclass trees as OVA) — export the "
            "OVA members instead")
    if spec.column_nums is None:
        raise ShifuError(ErrorCode.ERROR_MODEL_FILE_NOT_FOUND,
                         "tree spec lacks column_nums — retrain or pass "
                         "ColumnConfig-ordered features")
    by_num = {cc.columnNum: cc for cc in column_configs}
    col_info: Dict[int, dict] = {}
    for j, num in enumerate(spec.column_nums):
        cc = by_num[num]
        if cc.is_categorical():
            col_info[j] = {"column_num": num, "categories":
                           list(cc.bin_category or []), "boundaries": None}
        else:
            col_info[j] = {"column_num": num, "categories": None,
                           "boundaries": list(cc.bin_boundary or [])}

    d = _JavaDataOutput()
    d.write_int(4)                                       # TREE_FORMAT_VERSION
    d.write_utf(spec.algorithm)
    d.write_utf(spec.loss)
    d.write_boolean(False)                               # isClassification
    d.write_boolean(False)                               # isOneVsAll
    d.write_int(len(spec.column_nums))                   # inputCount

    selected = [by_num[n] for n in spec.column_nums]
    num_means = [(cc.columnNum, float(cc.columnStats.mean or 0.0))
                 for cc in column_configs
                 if not cc.is_categorical() and cc.columnStats.mean is not None]
    d.write_int(len(num_means))
    for num, mean in num_means:
        d.write_int(num)
        d.write_double(mean)
    d.write_int(len(selected))                           # columnIndexName
    for cc in selected:
        d.write_int(cc.columnNum)
        d.write_utf(cc.columnName)
    cats_cols = [cc for cc in column_configs
                 if cc.is_categorical() and cc.bin_category]
    d.write_int(len(cats_cols))
    for cc in cats_cols:
        d.write_int(cc.columnNum)
        cats = list(cc.bin_category)
        d.write_int(len(cats))
        for cat in cats:
            d.write_category(cat)
    d.write_int(len(spec.column_nums))                   # columnMapping
    for j, num in enumerate(spec.column_nums):
        d.write_int(num)
        d.write_int(j)

    if bags is None:
        out_trees = list(trees)
        if spec.algorithm == "GBT":
            # the format has no f_0 slot: the prior rides as a root-leaf
            # tree 0 with learningRate 1 (sum lr_i * predict_i reproduces
            # init_score + lr * sum predict exactly)
            out_trees = [_leaf_only_tree(spec.init_score)] + out_trees
        bags = [out_trees]
    d.write_int(len(bags))                               # version >= 4
    for bag in bags:
        d.write_int(len(bag))
        for t_i, tree in enumerate(bag):
            d.write_int(t_i)                             # treeId
            total = len(tree.split_feat)
            d.write_int(int(np.sum(np.asarray(tree.split_feat) >= 0)) * 2
                        + 1)                             # nodeNum
            _write_node(d, t_i, spec, tree, 0, col_info)
            is_prior = (spec.algorithm == "GBT" and t_i == 0
                        and total == 1)
            d.write_double(1.0 if spec.algorithm != "GBT" or is_prior
                           else spec.learning_rate)      # learningRate
            d.write_double(0.0)                          # rootWgtCnt (id 1)
            d.write_int(0)                               # per-tree features
    ioutil.atomic_write_bytes(path, gzip.compress(d.getvalue()))


# --------------------------------------- BinaryWDLSerializer (.wdl)

_WDL_ACTS = {"relu", "sigmoid"}         # reference buildHiddenLayers set


def _write_java_string(d: _JavaDataOutput, s: Optional[str]) -> None:
    """``dtrain/StringUtils.writeString``: int byte-length + raw UTF-8
    (0 = null) — NOT writeUTF."""
    if not s:
        d.write_int(0)
        return
    data = s.encode("utf-8")
    d.write_int(len(data))
    d._b.write(data)


def _write_double_list(d: _JavaDataOutput, vals) -> None:
    """``NNColumnStats.writeDoubleList``: int count + doubles (0 = null)."""
    if vals is None:
        d.write_int(0)
        return
    vals = [0.0 if v is None else float(v) for v in vals]
    d.write_int(len(vals))
    for v in vals:
        d.write_double(v)


def _woe_mean_std(woes, neg, pos):
    """``Normalizer.calculateWoeMeanAndStdDev``: bin-count-weighted WOE
    mean/stddev (``core/Normalizer.java:728-754``)."""
    if not woes or len(woes) < 2 or not neg:
        return 0.0, 0.0
    w = np.asarray([0.0 if x is None else float(x) for x in woes])
    cnt = np.asarray(neg, np.float64) + np.asarray(pos, np.float64)
    total = cnt.sum()
    if total <= 1:
        return 0.0, 0.0
    s = float((w * cnt).sum())
    sq = float((w * w * cnt).sum())
    mean = s / total
    std = float(np.sqrt(abs((sq - s * s / total) / (total - 1))))
    return mean, std


def _write_floats(d: _JavaDataOutput, a: np.ndarray) -> None:
    """Bulk big-endian f32 block (one buffer write, not per-element
    struct calls — WDL weight blocks run to millions of floats)."""
    d._b.write(np.ascontiguousarray(a, ">f4").tobytes())


def _write_dense_layer(d: _JavaDataOutput, w: np.ndarray, b: np.ndarray,
                       l2reg: float = 0.0) -> None:
    """``wdl/DenseLayer.write`` (Bytable, WEIGHTS/MODEL_SPEC): l2reg, in,
    out, presence-flagged weights [in][out] then bias [out]."""
    w = np.asarray(w, np.float32)
    b = np.asarray(b, np.float32).reshape(-1)
    d.write_float(l2reg)
    d.write_int(w.shape[0])
    d.write_int(w.shape[1])
    d.write_boolean(True)
    _write_floats(d, w)
    d.write_boolean(True)
    _write_floats(d, b)


def write_reference_wdl(path: str, spec, params: Dict,
                        column_configs=None, norm_type: str = "ZSCALE",
                        cutoff: float = 4.0) -> None:
    """Write a WDL model as a gzipped ``BinaryWDLSerializer`` stream
    (``core/dtrain/wdl/BinaryWDLSerializer.java:66-125``), the format
    ``IndependentWDLModel.loadFromStream`` consumes: version, reserved
    fields, normType string, NNColumnStats per column, then the
    ``WideAndDeep`` graph as Bytable MODEL_SPEC (``WideAndDeep.java:
    558-621``).  ``models.reference_import.load_reference_wdl`` is the
    round-trip oracle."""
    bad = [a for a in spec.activations if a.lower() not in _WDL_ACTS]
    if bad:
        raise ShifuError(ErrorCode.ERROR_UNSUPPORT_ALG,
                         f"activation(s) {bad}: the reference WDL runtime "
                         "only builds relu/sigmoid hidden activations")
    if not (spec.deep_enable and spec.wide_enable):
        raise ShifuError(ErrorCode.ERROR_UNSUPPORT_ALG,
                         "reference WideAndDeep scoring walks BOTH planes — "
                         "wide-only/deep-only specs have no faithful layout")
    n_cat = len(spec.cat_cardinalities)
    cat_ids = list(spec.cat_column_nums or range(n_cat))
    num_ids = list(spec.column_nums or range(spec.numeric_dim))

    d = _JavaDataOutput()
    d.write_int(1)                                  # WDL_FORMAT_VERSION
    d.write_float(0.0)                              # reserved
    d.write_float(0.0)
    d.write_double(0.0)
    d.write_utf("Reserved field")
    _write_java_string(d, norm_type)

    by_num = {cc.columnNum: cc for cc in (column_configs or [])}
    cs_nums = [n for n in num_ids + cat_ids if n in by_num]
    d.write_int(len(cs_nums))
    for num in cs_nums:                             # NNColumnStats.write
        cc = by_num[num]
        st, bn = cc.columnStats, cc.columnBinning
        d.write_int(num)
        _write_java_string(d, cc.columnName)
        d.write_byte(2 if cc.is_categorical() else 1)   # ColumnType C/N
        d.write_double(cutoff)
        d.write_double(st.mean or 0.0)
        d.write_double(st.stdDev or 0.0)
        wm, ws = _woe_mean_std(bn.binCountWoe, bn.binCountNeg, bn.binCountPos)
        d.write_double(wm)
        d.write_double(ws)
        wwm, wws = _woe_mean_std(bn.binWeightedWoe, bn.binCountNeg,
                                 bn.binCountPos)
        d.write_double(wwm)
        d.write_double(wws)
        _write_double_list(d, None if cc.is_categorical() else bn.binBoundary)
        cats = bn.binCategory or []
        d.write_int(len(cats))
        for cat in cats:
            _write_java_string(d, cat)
        _write_double_list(d, bn.binPosRate)
        _write_double_list(d, bn.binCountWoe)
        _write_double_list(d, bn.binWeightedWoe)

    # ---- WideAndDeep.write, serializationType = MODEL_SPEC
    deep = params["deep"]
    d.write_int(2)                                  # MODEL_SPEC
    d.write_boolean(True)                           # DenseInputLayer
    d.write_int(spec.numeric_dim)
    d.write_int(len(deep) - 1)                      # hidden DenseLayers
    for p in deep[:-1]:
        _write_dense_layer(d, p["w"], p["b"])
    d.write_boolean(True)                           # finalLayer
    _write_dense_layer(d, deep[-1]["w"], deep[-1]["b"])
    d.write_boolean(True)                           # EmbedLayer
    d.write_int(n_cat)
    for i, tab in enumerate(params["embed"]):       # EmbedFieldLayer.write
        tab = np.asarray(tab, np.float32)
        d.write_int(cat_ids[i])
        d.write_int(tab.shape[0])
        d.write_int(tab.shape[1])
        d.write_boolean(True)
        _write_floats(d, tab)
    d.write_boolean(True)                           # WideLayer
    d.write_int(n_cat)
    for i, wvec in enumerate(params["wide_cat"]):   # WideFieldLayer.write
        wvec = np.asarray(wvec, np.float32).reshape(-1)
        d.write_int(cat_ids[i])
        d.write_float(0.0)                          # l2reg
        d.write_int(len(wvec))
        d.write_boolean(True)
        _write_floats(d, wvec)
    d.write_boolean(True)                           # wide dense (numeric)
    _write_dense_layer(d, params["wide_num"], np.zeros(1, np.float32))
    d.write_boolean(True)                           # BiasLayer
    d.write_float(float(np.asarray(params["bias"]).reshape(-1)[0]))
    d.write_int(len(spec.activations))              # actiFuncs
    for a in spec.activations:
        d.write_utf(a.lower())
    # MODEL_SPEC extras
    d.write_int(n_cat)                              # idBinCateSizeMap
    for i, card in enumerate(spec.cat_cardinalities):
        d.write_int(cat_ids[i])
        d.write_int(int(card))
    d.write_int(spec.numeric_dim)
    for ids in (num_ids, cat_ids,
                [spec.embed_dim] * n_cat, cat_ids, list(spec.hidden_nodes)):
        d.write_int(len(ids))                       # SerializationUtil list
        for v in ids:
            d.write_int(int(v))
    d.write_float(0.0)                              # l2reg
    ioutil.atomic_write_bytes(path, gzip.compress(d.getvalue()))
