"""PMML export — reference ``core/pmml/PMMLTranslator.java:47,77`` +
``core/pmml/builder/impl/`` (16 builder classes) reduced to three builders
over ``xml.etree``: RegressionModel (LR), NeuralNetwork (NN),
MiningModel/TreeModel segmentation (GBT/RF).

The reference builds DataDictionary + LocalTransformations (zscore / woe
derived fields) + per-family model elements, verified against
jpmml-evaluator in its tests; here the same structure targets PMML 4.2.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import List, Optional

import numpy as np

from ..config import ColumnConfig
from ..config.model_config import ModelConfig, NormType

PMML_NS = "http://www.dmg.org/PMML-4_2"


def _pmml_root() -> ET.Element:
    root = ET.Element("PMML", {"version": "4.2", "xmlns": PMML_NS})
    header = ET.SubElement(root, "Header", {"copyright": "shifu-tpu"})
    ET.SubElement(header, "Application", {"name": "shifu-tpu"})
    return root


def _data_dictionary(root: ET.Element, columns: List[ColumnConfig],
                     target_name: str) -> None:
    dd = ET.SubElement(root, "DataDictionary",
                       {"numberOfFields": str(len(columns) + 1)})
    for cc in columns:
        ET.SubElement(dd, "DataField", {
            "name": cc.columnName,
            "optype": "categorical" if cc.is_categorical() else "continuous",
            "dataType": "string" if cc.is_categorical() else "double"})
    ET.SubElement(dd, "DataField", {"name": target_name,
                                    "optype": "categorical",
                                    "dataType": "string"})


def _mining_schema(parent: ET.Element, columns: List[ColumnConfig],
                   target_name: str) -> None:
    ms = ET.SubElement(parent, "MiningSchema")
    for cc in columns:
        ET.SubElement(ms, "MiningField", {"name": cc.columnName,
                                          "usageType": "active"})
    ET.SubElement(ms, "MiningField", {"name": target_name,
                                      "usageType": "target"})


def _derived_name(cc: ColumnConfig) -> str:
    return f"shifu::{cc.columnName}"


def _local_transformations(parent: ET.Element, columns: List[ColumnConfig],
                           norm_type: NormType, cutoff: float) -> None:
    """Per-column DerivedField: woe lookup for categorical / woe norms,
    clamped zscore for numeric (reference woe/zscore local-transform
    creators)."""
    lt = ET.SubElement(parent, "LocalTransformations")
    woe_like = norm_type.name.startswith("WOE") or norm_type in (
        NormType.HYBRID, NormType.WEIGHT_HYBRID)
    for cc in columns:
        df = ET.SubElement(lt, "DerivedField",
                           {"name": _derived_name(cc), "optype": "continuous",
                            "dataType": "double"})
        if cc.is_categorical() or woe_like:
            _woe_mapping(df, cc, weighted="WEIGHT" in norm_type.name)
        else:
            _zscore_transform(df, cc, cutoff)


def _woe_mapping(df: ET.Element, cc: ColumnConfig, weighted: bool) -> None:
    woes = (cc.columnBinning.binWeightedWoe if weighted
            else cc.columnBinning.binCountWoe) or []
    mv = ET.SubElement(df, "MapValues", {"outputColumn": "out",
                                         "defaultValue": "0.0"})
    ET.SubElement(mv, "FieldColumnPair", {"field": cc.columnName,
                                          "column": "in"})
    table = ET.SubElement(mv, "InlineTable")
    cats = cc.bin_category or []
    for cat, woe in zip(cats, woes):
        row = ET.SubElement(table, "row")
        ET.SubElement(row, "in").text = str(cat)
        ET.SubElement(row, "out").text = f"{woe:.6f}"


def _zscore_transform(df: ET.Element, cc: ColumnConfig, cutoff: float) -> None:
    mean, std = cc.mean(), cc.std_dev()
    lo, hi = mean - cutoff * std, mean + cutoff * std
    apply_div = ET.SubElement(df, "Apply", {"function": "/"})
    apply_sub = ET.SubElement(apply_div, "Apply", {"function": "-"})
    apply_max = ET.SubElement(apply_sub, "Apply", {"function": "max"})
    apply_min = ET.SubElement(apply_max, "Apply", {"function": "min"})
    ET.SubElement(apply_min, "FieldRef", {"field": cc.columnName})
    ET.SubElement(apply_min, "Constant").text = f"{hi:.6f}"
    ET.SubElement(apply_max, "Constant").text = f"{lo:.6f}"
    ET.SubElement(apply_sub, "Constant").text = f"{mean:.6f}"
    ET.SubElement(apply_div, "Constant").text = f"{std:.6f}"


# ----------------------------------------------------------------- models
def nn_to_pmml(model_config: ModelConfig, columns: List[ColumnConfig],
               spec, params) -> ET.ElementTree:
    """NeuralNetwork PMML (reference NNPmmlModelCreator +
    NeuralNetworkModelIntegrator)."""
    target = model_config.dataSet.targetColumnName or "target"
    root = _pmml_root()
    _data_dictionary(root, columns, target)
    nn = ET.SubElement(root, "NeuralNetwork", {
        "functionName": "regression",
        "activationFunction": _pmml_act(spec.activations[0]
                                        if spec.activations else "tanh")})
    _mining_schema(nn, columns, target)
    _local_transformations(nn, columns, model_config.normalize.normType,
                           model_config.normalize.stdDevCutOff)

    inputs = ET.SubElement(nn, "NeuralInputs",
                           {"numberOfInputs": str(spec.input_dim)})
    in_ids = []
    for i, cc in enumerate(columns[:spec.input_dim]):
        nid = f"0,{i}"
        ni = ET.SubElement(inputs, "NeuralInput", {"id": nid})
        df = ET.SubElement(ni, "DerivedField", {"optype": "continuous",
                                                "dataType": "double"})
        ET.SubElement(df, "FieldRef", {"field": _derived_name(cc)})
        in_ids.append(nid)
    # pad ids for expanded (onehot) feature spaces
    for i in range(len(in_ids), spec.input_dim):
        nid = f"0,{i}"
        ni = ET.SubElement(inputs, "NeuralInput", {"id": nid})
        df = ET.SubElement(ni, "DerivedField", {"optype": "continuous",
                                                "dataType": "double"})
        ET.SubElement(df, "FieldRef", {"field": f"feature_{i}"})
        in_ids.append(nid)

    prev_ids = in_ids
    for li, layer in enumerate(params):
        w = np.asarray(layer["w"])
        b = np.asarray(layer["b"])
        is_out = li == len(params) - 1
        act = _pmml_act(spec.output_activation if is_out else
                        spec.activations[li % max(1, len(spec.activations))])
        nl = ET.SubElement(nn, "NeuralLayer",
                           {"numberOfNeurons": str(w.shape[1]),
                            "activationFunction": act})
        ids = []
        for j in range(w.shape[1]):
            nid = f"{li + 1},{j}"
            neuron = ET.SubElement(nl, "Neuron",
                                   {"id": nid, "bias": f"{b[j]:.6f}"})
            for pi, pid in enumerate(prev_ids):
                ET.SubElement(neuron, "Con",
                              {"from": pid, "weight": f"{w[pi, j]:.6f}"})
            ids.append(nid)
        prev_ids = ids

    outs = ET.SubElement(nn, "NeuralOutputs", {"numberOfOutputs": "1"})
    no = ET.SubElement(outs, "NeuralOutput", {"outputNeuron": prev_ids[0]})
    df = ET.SubElement(no, "DerivedField", {"optype": "continuous",
                                            "dataType": "double"})
    ET.SubElement(df, "FieldRef", {"field": target})
    return ET.ElementTree(root)


def lr_to_pmml(model_config: ModelConfig, columns: List[ColumnConfig],
               spec, params) -> ET.ElementTree:
    """RegressionModel PMML with logit normalization (reference
    RegressionPmmlModelCreator)."""
    target = model_config.dataSet.targetColumnName or "target"
    root = _pmml_root()
    _data_dictionary(root, columns, target)
    rm = ET.SubElement(root, "RegressionModel", {
        "functionName": "regression", "normalizationMethod": "logit"})
    _mining_schema(rm, columns, target)
    _local_transformations(rm, columns, model_config.normalize.normType,
                           model_config.normalize.stdDevCutOff)
    w = np.asarray(params[0]["w"])[:, 0]
    b = float(np.asarray(params[0]["b"])[0])
    table = ET.SubElement(rm, "RegressionTable", {"intercept": f"{b:.6f}"})
    for i, cc in enumerate(columns[:len(w)]):
        ET.SubElement(table, "NumericPredictor",
                      {"name": _derived_name(cc), "exponent": "1",
                       "coefficient": f"{w[i]:.6f}"})
    return ET.ElementTree(root)


def tree_to_pmml(model_config: ModelConfig, columns: List[ColumnConfig],
                 spec, trees) -> ET.ElementTree:
    """MiningModel with TreeModel segments (reference TreeEnsemblePmml
    translator): splits reference bin indices via derived discretized
    fields."""
    target = model_config.dataSet.targetColumnName or "target"
    root = _pmml_root()
    _data_dictionary(root, columns, target)
    mm = ET.SubElement(root, "MiningModel", {"functionName": "regression"})
    _mining_schema(mm, columns, target)
    seg = ET.SubElement(mm, "Segmentation", {
        "multipleModelMethod": "sum" if spec.algorithm == "GBT" else "average"})
    col_by_idx = {j: cc for j, cc in enumerate(columns)}
    for ti, t in enumerate(trees):
        s = ET.SubElement(seg, "Segment", {"id": str(ti)})
        ET.SubElement(s, "True")
        tm = ET.SubElement(s, "TreeModel", {"functionName": "regression",
                                            "splitCharacteristic": "binarySplit"})
        _mining_schema(tm, columns, target)
        root_node = ET.SubElement(tm, "Node", {"id": "0", "score": "0"})
        ET.SubElement(root_node, "True")
        _emit_tree_node(root_node, t, 0, col_by_idx, spec.n_bins)
    return ET.ElementTree(root)


def _emit_tree_node(parent: ET.Element, t, node: int, col_by_idx,
                    n_bins: int) -> None:
    feat = int(t.split_feat[node]) if node < len(t.split_feat) else -1
    parent.set("score", f"{float(t.leaf_value[node]):.6f}")
    if feat < 0:
        return
    cc = col_by_idx.get(feat)
    fname = cc.columnName if cc else f"feature_{feat}"
    left_bins = [str(b) for b in np.flatnonzero(t.left_mask[node])]
    for child, bins_attr in ((2 * node + 1, left_bins), (2 * node + 2, None)):
        n = ET.SubElement(parent, "Node", {"id": str(child), "score": "0"})
        if bins_attr is not None:
            pred = ET.SubElement(n, "SimpleSetPredicate",
                                 {"field": f"bin({fname})",
                                  "booleanOperator": "isIn"})
            arr = ET.SubElement(pred, "Array",
                                {"type": "int", "n": str(len(bins_attr))})
            arr.text = " ".join(bins_attr)
        else:
            ET.SubElement(n, "True")
        _emit_tree_node(n, t, child, col_by_idx, n_bins)


def _pmml_act(name: str) -> str:
    m = {"sigmoid": "logistic", "tanh": "tanh", "relu": "rectifier",
         "linear": "identity", "leakyrelu": "rectifier", "swish": "rectifier",
         "ptanh": "tanh"}
    return m.get((name or "sigmoid").lower(), "logistic")


def write_pmml(tree: ET.ElementTree, path: str) -> None:
    ET.indent(tree, space="  ")
    tree.write(path, xml_declaration=True, encoding="utf-8")
