"""PMML export — reference ``core/pmml/PMMLTranslator.java:47,77`` +
``core/pmml/builder/impl/`` (16 builder classes) reduced to three builders
over ``xml.etree``: RegressionModel (LR), NeuralNetwork (NN),
MiningModel/TreeModel segmentation (GBT/RF), targeting PMML 4.2.

Score parity with the native scorer is the contract (the reference verifies
against jpmml-evaluator): every DerivedField is computed from the SAME
Normalizer tables used in training —

- numeric z-score family → clamped zscore ``Apply`` (mapMissingTo=0 ≙
  missing→mean);
- numeric woe/discrete families → ``Discretize`` whose bins output the
  exact per-bin normalized value;
- categorical (any width-1 norm) → ``MapValues`` category→value computed by
  ``NormalizedColumn.transform`` on each bin index;
- GBT trees: leaf values pre-scaled by shrinkage, an init-score constant
  segment, and a logistic-link OutputField for log loss.

- one-hot-expanding norms (ONEHOT / ZSCALE_ONEHOT categorical) → one
  indicator ``MapValues`` DerivedField per bin (the last = missing/unseen
  indicator); net inputs / regression predictors bind to the flat expanded
  feature list in norm order.
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import List, Optional

import numpy as np

from ..config import ColumnConfig
from ..config.model_config import ModelConfig, NormType
from ..ops.normalize import NormalizedColumn

PMML_NS = "http://www.dmg.org/PMML-4_2"

ZSCORE_FAMILY = {NormType.ZSCALE, NormType.ZSCORE, NormType.OLD_ZSCALE,
                 NormType.OLD_ZSCORE, NormType.HYBRID, NormType.WEIGHT_HYBRID,
                 NormType.ZSCALE_ONEHOT, NormType.ZSCALE_INDEX,
                 NormType.ZSCORE_INDEX}


class PmmlUnsupportedError(ValueError):
    pass


def _pmml_root() -> ET.Element:
    root = ET.Element("PMML", {"version": "4.2", "xmlns": PMML_NS})
    header = ET.SubElement(root, "Header", {"copyright": "shifu-tpu"})
    ET.SubElement(header, "Application", {"name": "shifu-tpu"})
    return root


def _data_dictionary(root: ET.Element, columns: List[ColumnConfig],
                     target_name: str) -> None:
    dd = ET.SubElement(root, "DataDictionary",
                       {"numberOfFields": str(len(columns) + 1)})
    for cc in columns:
        ET.SubElement(dd, "DataField", {
            "name": cc.columnName,
            "optype": "categorical" if cc.is_categorical() else "continuous",
            "dataType": "string" if cc.is_categorical() else "double"})
    ET.SubElement(dd, "DataField", {"name": target_name,
                                    "optype": "categorical",
                                    "dataType": "string"})


def _mining_schema(parent: ET.Element, columns: List[ColumnConfig],
                   target_name: str) -> None:
    ms = ET.SubElement(parent, "MiningSchema")
    for cc in columns:
        ET.SubElement(ms, "MiningField", {"name": cc.columnName,
                                          "usageType": "active"})
    ET.SubElement(ms, "MiningField", {"name": target_name,
                                      "usageType": "target"})


def _derived_name(cc: ColumnConfig) -> str:
    return f"shifu::{cc.columnName}"


def _categorical_value_table(cc: ColumnConfig, nc: NormalizedColumn
                             ) -> np.ndarray:
    """Exact per-bin normalized output (incl. the trailing missing bin)."""
    nb = cc.num_bins() + 1
    idx = np.arange(nb)
    return nc.transform(np.zeros(nb), np.zeros(nb, bool), idx)[:, 0]


def _numeric_bin_values(cc: ColumnConfig, nc: NormalizedColumn) -> np.ndarray:
    nb = cc.num_bins() + 1
    idx = np.arange(nb)
    # values/valid only matter for zscore paths, which don't take this branch
    return nc.transform(np.zeros(nb), np.ones(nb, bool), idx)[:, 0]


def _local_transformations(parent: ET.Element, columns: List[ColumnConfig],
                           model_config: ModelConfig) -> List[str]:
    """Emit one DerivedField per normalized FEATURE and return the flat
    ordered name list — one-hot-expanding norms contribute one indicator
    field per bin (reference ``WoeZscorePmmlElementCreator`` +
    ``ZscoreLocalTransformCreator`` family, ``core/pmml/builder/impl/``),
    so net input i == flat feature i for every norm type."""
    norm_type = model_config.normalize.normType
    cutoff = model_config.normalize.stdDevCutOff
    lt = ET.SubElement(parent, "LocalTransformations")
    names: List[str] = []
    for cc in columns:
        nc = NormalizedColumn(cc, norm_type, cutoff)
        if nc.width != 1:
            # one-hot expansion: feature j = [bin(col) == j]; the last
            # feature is the missing-bin indicator (unseen/missing -> 1).
            # Categorical bins one-hot via MapValues; numeric bins (plain
            # NormType.ONEHOT) via per-interval Discretize indicators.
            nb = nc.width - 1
            cats = list(cc.bin_category or [])
            bounds = list(cc.bin_boundary or [])
            for j in range(nc.width):
                name = f"{_derived_name(cc)}_{j}"
                df = ET.SubElement(lt, "DerivedField",
                                   {"name": name, "optype": "continuous",
                                    "dataType": "double"})
                missing_feat = j == nb
                if cc.is_categorical():
                    mv = ET.SubElement(df, "MapValues", {
                        "outputColumn": "out", "dataType": "double",
                        "defaultValue": "1" if missing_feat else "0",
                        "mapMissingTo": "1" if missing_feat else "0"})
                    ET.SubElement(mv, "FieldColumnPair",
                                  {"field": cc.columnName, "column": "in"})
                    table = ET.SubElement(mv, "InlineTable")
                    for bi, cat in enumerate(cats):
                        row = ET.SubElement(table, "row")
                        ET.SubElement(row, "in").text = str(cat)
                        ET.SubElement(row, "out").text = \
                            "1" if (bi == j and not missing_feat) else "0"
                else:
                    disc = ET.SubElement(df, "Discretize", {
                        "field": cc.columnName, "dataType": "double",
                        "defaultValue": "0",
                        "mapMissingTo": "1" if missing_feat else "0"})
                    if not missing_feat and j < len(bounds):
                        b = ET.SubElement(disc, "DiscretizeBin",
                                          {"binValue": "1"})
                        iv = {"closure": "closedOpen"}
                        if np.isfinite(bounds[j]):
                            iv["leftMargin"] = f"{bounds[j]:.6g}"
                        if j + 1 < len(bounds) and np.isfinite(bounds[j + 1]):
                            iv["rightMargin"] = f"{bounds[j + 1]:.6g}"
                        ET.SubElement(b, "Interval", iv)
                names.append(name)
            continue
        df = ET.SubElement(lt, "DerivedField",
                           {"name": _derived_name(cc), "optype": "continuous",
                            "dataType": "double"})
        if cc.is_categorical():
            vals = _categorical_value_table(cc, nc)
            _map_values(df, cc, vals)
        elif norm_type in ZSCORE_FAMILY:
            _zscore_transform(df, cc, cutoff)
        else:
            # per-bin table norms (WOE / WOE_ZSCALE / DISCRETE_* / ...)
            vals = _numeric_bin_values(cc, nc)
            _discretize(df, cc, vals)
        names.append(_derived_name(cc))
    return names


def _map_values(df: ET.Element, cc: ColumnConfig, vals: np.ndarray) -> None:
    mv = ET.SubElement(df, "MapValues", {
        "outputColumn": "out", "dataType": "double",
        # unseen / missing category -> the missing-bin value
        "defaultValue": f"{vals[-1]:.6f}", "mapMissingTo": f"{vals[-1]:.6f}"})
    ET.SubElement(mv, "FieldColumnPair", {"field": cc.columnName,
                                          "column": "in"})
    table = ET.SubElement(mv, "InlineTable")
    for cat, v in zip(cc.bin_category or [], vals[:-1]):
        row = ET.SubElement(table, "row")
        ET.SubElement(row, "in").text = str(cat)
        ET.SubElement(row, "out").text = f"{v:.6f}"


def _discretize(df: ET.Element, cc: ColumnConfig, vals: np.ndarray) -> None:
    """Numeric bin-table norm: Discretize where each bin outputs its
    normalized value directly (missing -> missing-bin value)."""
    bounds = cc.bin_boundary or []
    disc = ET.SubElement(df, "Discretize", {
        "field": cc.columnName, "dataType": "double",
        "defaultValue": f"{vals[-1]:.6f}", "mapMissingTo": f"{vals[-1]:.6f}"})
    for i in range(len(bounds)):
        b = ET.SubElement(disc, "DiscretizeBin",
                          {"binValue": f"{vals[i]:.6f}"})
        iv = {"closure": "closedOpen"}
        if np.isfinite(bounds[i]):
            iv["leftMargin"] = f"{bounds[i]:.6g}"
        if i + 1 < len(bounds) and np.isfinite(bounds[i + 1]):
            iv["rightMargin"] = f"{bounds[i + 1]:.6g}"
        ET.SubElement(b, "Interval", iv)


def _zscore_transform(df: ET.Element, cc: ColumnConfig, cutoff: float) -> None:
    mean, std = cc.mean(), cc.std_dev()
    lo, hi = mean - cutoff * std, mean + cutoff * std
    apply_div = ET.SubElement(df, "Apply", {"function": "/",
                                            "mapMissingTo": "0"})
    apply_sub = ET.SubElement(apply_div, "Apply", {"function": "-"})
    apply_max = ET.SubElement(apply_sub, "Apply", {"function": "max"})
    apply_min = ET.SubElement(apply_max, "Apply", {"function": "min"})
    ET.SubElement(apply_min, "FieldRef", {"field": cc.columnName})
    ET.SubElement(apply_min, "Constant").text = f"{hi:.6f}"
    ET.SubElement(apply_max, "Constant").text = f"{lo:.6f}"
    ET.SubElement(apply_sub, "Constant").text = f"{mean:.6f}"
    ET.SubElement(apply_div, "Constant").text = f"{std:.6f}"


def _fmt_list(vals) -> str:
    return "[" + ", ".join(str(v) for v in (vals or [])) + "]"


def _model_stats(parent: ET.Element, columns: List[ColumnConfig],
                 concise: bool) -> None:
    """ModelStats with per-input UnivariateStats (reference
    ``core/pmml/builder/impl/ModelStatsCreator.java:60-230``): numeric
    columns carry NumericInfo (+ ContStats bin intervals unless concise),
    categoricals a DiscrStats count array (+ bin-count Extensions unless
    concise)."""
    ms = ET.SubElement(parent, "ModelStats")
    for cc in columns:
        us = ET.SubElement(ms, "UnivariateStats", {"field": cc.columnName})
        st, bn = cc.columnStats, cc.columnBinning
        pos = bn.binCountPos or []
        neg = bn.binCountNeg or []

        def extensions(el: ET.Element) -> None:
            for name, vals in (("BinCountPos", pos), ("BinCountNeg", neg),
                               ("BinWeightedCountPos", bn.binWeightedPos),
                               ("BinWeightedCountNeg", bn.binWeightedNeg),
                               ("BinPosRate", bn.binPosRate)):
                ET.SubElement(el, "Extension",
                              {"name": name, "value": _fmt_list(vals)})
        if cc.is_categorical():
            ds = ET.SubElement(us, "DiscrStats")
            if not concise:      # PMML content model: Extension* first
                extensions(ds)
            counts = [int(p) + int(n) for p, n in zip(pos, neg)]
            arr = ET.SubElement(ds, "Array", {"type": "int",
                                              "n": str(len(counts))})
            arr.text = " ".join(str(v) for v in counts)
        else:
            attrs = {}
            for k, v in (("minimum", st.min), ("maximum", st.max),
                         ("mean", st.mean), ("median", st.median),
                         ("standardDeviation", st.stdDev)):
                if v is not None:
                    attrs[k] = str(v)
            ET.SubElement(us, "NumericInfo", attrs)
            if not concise:
                cs = ET.SubElement(us, "ContStats")
                extensions(cs)   # PMML content model: Extension* first
                bb = bn.binBoundary or []
                for i in range(len(bb)):
                    right = bb[i + 1] if i + 1 < len(bb) else float("inf")
                    attrs_i = {"closure": "openClosed"}
                    # +-inf margins are OMITTED (xs:double has no "inf"
                    # lexical form; same convention as every Discretize
                    # interval this file emits)
                    if np.isfinite(bb[i]):
                        attrs_i["leftMargin"] = str(bb[i])
                    if np.isfinite(right):
                        attrs_i["rightMargin"] = str(right)
                    ET.SubElement(cs, "Interval", attrs_i)


# ----------------------------------------------------------------- models
def nn_to_pmml(model_config: ModelConfig, columns: List[ColumnConfig],
               spec, params, concise: bool = False) -> ET.ElementTree:
    """NeuralNetwork PMML (reference NNPmmlModelCreator +
    NeuralNetworkModelIntegrator).  One-hot-expanding norms contribute one
    indicator field per bin; net input i == flat feature i."""
    target = model_config.dataSet.targetColumnName or "target"
    root = _pmml_root()
    _data_dictionary(root, columns, target)
    nn = ET.SubElement(root, "NeuralNetwork", {
        "functionName": "regression",
        "activationFunction": _pmml_act(spec.activations[0]
                                        if spec.activations else "tanh")})
    _mining_schema(nn, columns, target)
    _model_stats(nn, columns, concise)
    feature_names = _local_transformations(nn, columns, model_config)
    if spec.input_dim != len(feature_names):
        raise PmmlUnsupportedError(
            f"net input dim {spec.input_dim} != {len(feature_names)} "
            "normalized features — the model was trained on a different "
            "column/norm configuration")

    inputs = ET.SubElement(nn, "NeuralInputs",
                           {"numberOfInputs": str(spec.input_dim)})
    in_ids = []
    for i, fname in enumerate(feature_names):
        nid = f"0,{i}"
        ni = ET.SubElement(inputs, "NeuralInput", {"id": nid})
        df = ET.SubElement(ni, "DerivedField", {"optype": "continuous",
                                                "dataType": "double"})
        ET.SubElement(df, "FieldRef", {"field": fname})
        in_ids.append(nid)

    prev_ids = in_ids
    for li, layer in enumerate(params):
        w = np.asarray(layer["w"])
        b = np.asarray(layer["b"])
        is_out = li == len(params) - 1
        act = _pmml_act(spec.output_activation if is_out else
                        spec.activations[li % max(1, len(spec.activations))])
        nl = ET.SubElement(nn, "NeuralLayer",
                           {"numberOfNeurons": str(w.shape[1]),
                            "activationFunction": act})
        ids = []
        for j in range(w.shape[1]):
            nid = f"{li + 1},{j}"
            neuron = ET.SubElement(nl, "Neuron",
                                   {"id": nid, "bias": f"{b[j]:.6f}"})
            for pi, pid in enumerate(prev_ids):
                ET.SubElement(neuron, "Con",
                              {"from": pid, "weight": f"{w[pi, j]:.6f}"})
            ids.append(nid)
        prev_ids = ids

    outs = ET.SubElement(nn, "NeuralOutputs", {"numberOfOutputs": "1"})
    no = ET.SubElement(outs, "NeuralOutput", {"outputNeuron": prev_ids[0]})
    df = ET.SubElement(no, "DerivedField", {"optype": "continuous",
                                            "dataType": "double"})
    ET.SubElement(df, "FieldRef", {"field": target})
    return ET.ElementTree(root)


def lr_to_pmml(model_config: ModelConfig, columns: List[ColumnConfig],
               spec, params, concise: bool = False) -> ET.ElementTree:
    """RegressionModel PMML with logit normalization (reference
    RegressionPmmlModelCreator).  One-hot norms yield one predictor per
    expanded indicator feature."""
    target = model_config.dataSet.targetColumnName or "target"
    root = _pmml_root()
    _data_dictionary(root, columns, target)
    rm = ET.SubElement(root, "RegressionModel", {
        "functionName": "regression", "normalizationMethod": "logit"})
    _mining_schema(rm, columns, target)
    _model_stats(rm, columns, concise)
    feature_names = _local_transformations(rm, columns, model_config)
    if spec.input_dim != len(feature_names):
        raise PmmlUnsupportedError(
            f"LR input dim {spec.input_dim} != {len(feature_names)} "
            "normalized features — the model was trained on a different "
            "column/norm configuration")
    w = np.asarray(params[0]["w"])[:, 0]
    b = float(np.asarray(params[0]["b"])[0])
    table = ET.SubElement(rm, "RegressionTable", {"intercept": f"{b:.6f}"})
    for i, fname in enumerate(feature_names):
        ET.SubElement(table, "NumericPredictor",
                      {"name": fname, "exponent": "1",
                       "coefficient": f"{w[i]:.6f}"})
    return ET.ElementTree(root)


def tree_to_pmml(model_config: ModelConfig, columns: List[ColumnConfig],
                 spec, trees, concise: bool = False) -> ET.ElementTree:
    """MiningModel with TreeModel segments.  Split predicates test the
    ``bin(col)`` derived fields defined in LocalTransformations (Discretize /
    MapValues to bin index); GBT leaves are pre-scaled by shrinkage with an
    init-score constant segment and a logistic OutputField for log loss —
    scores match the native ``IndependentTreeModel.compute`` exactly (modulo
    GBT squared-loss clipping, which PMML omits)."""
    target = model_config.dataSet.targetColumnName or "target"
    is_gbt = spec.algorithm == "GBT"
    root = _pmml_root()
    _data_dictionary(root, columns, target)
    mm = ET.SubElement(root, "MiningModel", {"functionName": "regression"})
    _mining_schema(mm, columns, target)
    _model_stats(mm, columns, concise)
    _bin_index_transforms(mm, columns)
    if is_gbt and spec.loss == "log":
        _logistic_output(mm)
    seg = ET.SubElement(mm, "Segmentation", {
        "multipleModelMethod": "sum" if is_gbt else "average"})
    col_by_idx = {j: cc for j, cc in enumerate(columns)}
    scale = spec.learning_rate if is_gbt else 1.0
    if is_gbt and spec.init_score:
        s = ET.SubElement(seg, "Segment", {"id": "init"})
        ET.SubElement(s, "True")
        tm = ET.SubElement(s, "TreeModel", {"functionName": "regression"})
        _mining_schema(tm, columns, target)
        node = ET.SubElement(tm, "Node", {"id": "0",
                                          "score": f"{spec.init_score:.6f}"})
        ET.SubElement(node, "True")
    for ti, t in enumerate(trees):
        s = ET.SubElement(seg, "Segment", {"id": str(ti)})
        ET.SubElement(s, "True")
        tm = ET.SubElement(s, "TreeModel", {"functionName": "regression",
                                            "splitCharacteristic": "binarySplit"})
        _mining_schema(tm, columns, target)
        root_node = ET.SubElement(tm, "Node", {"id": "0", "score": "0"})
        ET.SubElement(root_node, "True")
        _emit_tree_node(root_node, t, 0, col_by_idx, scale)
    return ET.ElementTree(root)


def _bin_index_transforms(mm: ET.Element, columns: List[ColumnConfig]) -> None:
    """DerivedField ``bin(col)`` = the bin index (integer), matching
    ``ColumnBinner``: numeric Discretize over boundaries, categorical
    MapValues; missing/unseen -> the trailing missing bin."""
    lt = ET.SubElement(mm, "LocalTransformations")
    for cc in columns:
        nb = cc.num_bins()
        df = ET.SubElement(lt, "DerivedField",
                           {"name": f"bin({cc.columnName})",
                            "optype": "categorical", "dataType": "integer"})
        if cc.is_categorical():
            mv = ET.SubElement(df, "MapValues", {
                "outputColumn": "out", "dataType": "integer",
                "defaultValue": str(nb), "mapMissingTo": str(nb)})
            ET.SubElement(mv, "FieldColumnPair", {"field": cc.columnName,
                                                  "column": "in"})
            table = ET.SubElement(mv, "InlineTable")
            for i, cat in enumerate(cc.bin_category or []):
                row = ET.SubElement(table, "row")
                ET.SubElement(row, "in").text = str(cat)
                ET.SubElement(row, "out").text = str(i)
        else:
            bounds = cc.bin_boundary or []
            disc = ET.SubElement(df, "Discretize", {
                "field": cc.columnName, "dataType": "integer",
                "defaultValue": str(nb), "mapMissingTo": str(nb)})
            for i in range(len(bounds)):
                b = ET.SubElement(disc, "DiscretizeBin", {"binValue": str(i)})
                iv = {"closure": "closedOpen"}
                if np.isfinite(bounds[i]):
                    iv["leftMargin"] = f"{bounds[i]:.6g}"
                if i + 1 < len(bounds) and np.isfinite(bounds[i + 1]):
                    iv["rightMargin"] = f"{bounds[i + 1]:.6g}"
                ET.SubElement(b, "Interval", iv)


def _logistic_output(mm: ET.Element) -> None:
    out = ET.SubElement(mm, "Output")
    ET.SubElement(out, "OutputField", {"name": "rawSum", "optype": "continuous",
                                       "dataType": "double",
                                       "feature": "predictedValue"})
    of = ET.SubElement(out, "OutputField", {"name": "score",
                                            "optype": "continuous",
                                            "dataType": "double",
                                            "feature": "transformedValue"})
    div = ET.SubElement(of, "Apply", {"function": "/"})
    ET.SubElement(div, "Constant").text = "1"
    plus = ET.SubElement(div, "Apply", {"function": "+"})
    ET.SubElement(plus, "Constant").text = "1"
    expo = ET.SubElement(plus, "Apply", {"function": "exp"})
    neg = ET.SubElement(expo, "Apply", {"function": "*"})
    ET.SubElement(neg, "Constant").text = "-1"
    ET.SubElement(neg, "FieldRef", {"field": "rawSum"})


def _emit_tree_node(parent: ET.Element, t, node: int, col_by_idx,
                    scale: float) -> None:
    feat = int(t.split_feat[node]) if node < len(t.split_feat) else -1
    parent.set("score", f"{float(t.leaf_value[node]) * scale:.6f}")
    if feat < 0:
        return
    cc = col_by_idx.get(feat)
    fname = cc.columnName if cc else f"feature_{feat}"
    left_bins = [str(b) for b in np.flatnonzero(t.left_mask[node])]
    for child, bins_attr in ((2 * node + 1, left_bins), (2 * node + 2, None)):
        n = ET.SubElement(parent, "Node", {"id": str(child), "score": "0"})
        if bins_attr is not None:
            pred = ET.SubElement(n, "SimpleSetPredicate",
                                 {"field": f"bin({fname})",
                                  "booleanOperator": "isIn"})
            arr = ET.SubElement(pred, "Array",
                                {"type": "int", "n": str(len(bins_attr))})
            arr.text = " ".join(bins_attr)
        else:
            ET.SubElement(n, "True")
        _emit_tree_node(n, t, child, col_by_idx, scale)


def _pmml_act(name: str) -> str:
    m = {"sigmoid": "logistic", "tanh": "tanh", "relu": "rectifier",
         "linear": "identity", "leakyrelu": "rectifier", "swish": "rectifier",
         "ptanh": "tanh"}
    return m.get((name or "sigmoid").lower(), "logistic")


def write_pmml(tree: ET.ElementTree, path: str) -> None:
    ET.indent(tree, space="  ")
    tree.write(path, xml_declaration=True, encoding="utf-8")
