"""Deterministic fault-injection harness — makes recovery *testable*.

The reference system inherited its fault story from Hadoop (failed map
tasks re-run, Guagua restarts from the last iteration); proving OUR
recovery paths work needs a way to make the pipeline fail at an exact,
named point, deterministically.  This module is that switchboard: hot
paths call :func:`fire` at phase boundaries (norm shard commits, stats
chunks, train trees/epochs, reader/spill IO) and a spec names which of
those points should fail, how.

Spec grammar (env ``SHIFU_TPU_FAULTS`` or property ``-Dshifu.faults``)::

    clause[,clause...]
    clause := <site>:<point>=<value>:<action>[@<count>]

    SHIFU_TPU_FAULTS="norm:shard=3:ioerror,train:tree=17:kill"
    SHIFU_TPU_FAULTS="reader:file=0:ioerror@2"    # first 2 hits fail

Sites/points wired today (grep ``faults.fire`` for the live set):

    norm:shard=<k>      before shard k's commit record lands
    norm:wire=<k>       before shard k's rows append to the direct-to-
                        wire plane (a kill leaves truncatable tail
                        bytes past the last committed wire manifest)
    rawcache:commit=0   raw-cache manifest commit (a kill leaves only
                        tmp files — absent manifest == absent cache)
    stats:chunk=<ci>    before chunk ci is absorbed by the accumulators
    train:tree=<ti>     after tree ti's progress line (GBT/RF)
    train:superbatch=<k>  after disk-tail super-batch drain k lands its
                        trees on host (streamed GBT coarse-to-fine pend
                        drain / streamed RF tail batch commit) — the
                        checkpoint-cadence boundary of the one-pass tail
                        schedule
    train:epoch=<e>     after epoch e's progress line (NN/LR/WDL/SVM)
    train:bag=<b>       before kernel-SVM bag b trains
    reader:file=<i>     opening the i-th raw input file
    shards:shard=<i>    decoding the i-th materialized npz shard
    spill:append=<k>    spill write-through of shard k
    spill:manifest=0    spill manifest commit
    step:phase=<name>   entering a named processor phase span
    obs:heartbeat=<b>   before heartbeat b's atomic commit (obs/health) —
                        a kill here proves a death mid-heartbeat leaves
                        the previous valid health file, never a torn one
    serve:request=<k>   before serving batch k's device launch — an
                        ioerror fails exactly that batch's tickets and
                        must leave the scorer/registry serviceable
    serve:swap=<key>    after a hot-swap candidate is built+warmed,
                        before the journal commit and the live flip — a
                        crash here must leave the PREVIOUS model live,
                        scoring bit-identically
    serve:replica=<name>  in a fleet worker's HTTP /score path, before
                        the request enqueues — a kill here is the
                        replica-death drill: the router must drain the
                        dead backend and requeue un-launched tickets on
                        a peer so every accepted request completes
    serve:admit=<k>     while the k-th shed submit is being rejected at
                        the admission cap — an ioerror there must leave
                        the queue depth and SLO shed accounting
                        consistent; a kill is the die-during-shed drill
    obs:scorelog=<k>    before score-log segment k's atomic rotation
                        commit (the os.replace that drops the .open torn
                        marker) — a kill here leaves a torn final
                        segment readers skip with a surfaced count;
                        committed segments stay intact and the next
                        writer sweeps the orphan and continues

Actions:

- ``ioerror``   raise :class:`InjectedFault` (an ``OSError``) — exercises
  the transient-IO retry ladder and step-failure paths in-process;
- ``kill``      ``os._exit(137)`` — a SIGKILL-equivalent hard death (no
  atexit, no flushing); subprocess tests resume afterwards;
- ``truncate``  truncate the target file to half its size, then hard-exit
  — manufactures a torn, committed-looking artifact.

Each clause fires ``count`` times (default 1) then disarms, so a retry
ladder can be tested to succeed on attempt 2 (``@1``, the default) or be
driven to exhaustion (``@99``).  Parsing is lazy and cached; with no
spec configured :func:`fire` is a dict-lookup no-op.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional, Tuple

log = logging.getLogger(__name__)

_ACTIONS = ("ioerror", "kill", "truncate")

# Declared fault sites: (site, point) -> one-line description of the
# boundary.  The ``fault-site`` lint rule (shifu_tpu/lint) checks every
# ``faults.fire("site", "point", ...)`` literal against this manifest —
# an undeclared site would be un-triggerable from a spec that follows
# the documented grammar, and a typo'd one would silently never fire.
SITES: dict = {
    ("norm", "shard"): "before shard k's commit record lands",
    ("stats", "chunk"): "before chunk ci is absorbed by the accumulators",
    ("train", "tree"): "after tree ti's progress line (GBT/RF)",
    ("train", "superbatch"): "after disk-tail super-batch drain k lands",
    ("train", "epoch"): "after epoch e's progress line (NN/LR/WDL/SVM)",
    ("train", "bag"): "before kernel-SVM bag b trains",
    ("reader", "file"): "opening the i-th raw input file",
    ("shards", "shard"): "decoding the i-th materialized npz shard",
    ("spill", "append"): "spill write-through of shard k",
    ("spill", "manifest"): "spill manifest commit",
    ("step", "phase"): "entering a named processor phase span",
    ("obs", "heartbeat"): "before heartbeat b's atomic commit",
    ("obs", "scorelog"): "before score-log segment k's atomic rotation "
                         "commit — a kill leaves a torn .open final "
                         "segment readers skip; prior segments intact, "
                         "the next writer recovers",
    ("serve", "request"): "before serving batch k's device launch",
    ("serve", "swap"): "after a hot-swap candidate is built+warmed, "
                       "before the journal commit and the live flip",
    ("serve", "replica"): "in a fleet worker's /score path before the "
                          "request enqueues — a kill is the replica-"
                          "death drill (router drains + requeues)",
    ("serve", "admit"): "while shed #k is being rejected at the "
                        "admission cap (queue at maxQueueRows) — an "
                        "ioerror must leave the queue depth and the "
                        "SLO shed accounting consistent; a kill is the "
                        "die-during-shed drill",
    ("dcn", "step"): "at elastic step s's boundary, before this "
                     "controller's contribution commit — a kill here is "
                     "the worker-loss drill the quorum must mask",
    ("train", "rejoin"): "when a rejoined controller starts replaying "
                         "committed step s from the close journal",
    ("refresh", "trigger"): "before a refresh trigger decision record "
                            "commits (the cycle has not started yet)",
    ("refresh", "promote"): "after the candidate passes the AUC gate, "
                            "before the registry hot-swap — a crash "
                            "here must leave the incumbent live and "
                            "bit-identical, and the refresh journal "
                            "must resume the cycle at the gate",
    ("refresh", "rollback"): "before a probation-failure rollback "
                             "re-flips the registry to the previous "
                             "generation",
    ("rawcache", "commit"): "before the raw-cache manifest commit — a "
                            "kill/truncate here must leave only tmp "
                            "files (absent manifest == absent cache) "
                            "the next writer sweeps and rebuilds",
    ("norm", "wire"): "before shard k's rows append to the wire plane "
                      "— a kill here leaves raw-file tail bytes past "
                      "the last committed manifest; the journal resume "
                      "truncates them and re-lands the shard",
}


def is_declared_site(site: str, point: str) -> bool:
    return (site, point) in SITES

_clauses: Optional[Dict[Tuple[str, str, str], List]] = None  # [action, left]


class InjectedFault(OSError):
    """An injected IO failure (distinguishable from real OS errors)."""


def _spec_string() -> str:
    spec = os.environ.get("SHIFU_TPU_FAULTS")
    if spec:
        return spec
    from .config import environment
    return environment.get_property("shifu.faults") or ""


def parse_spec(spec: str) -> Dict[Tuple[str, str, str], List]:
    """``"norm:shard=3:ioerror@2"`` -> {("norm","shard","3"): ["ioerror", 2]}.

    Malformed clauses fail loudly — a typo'd fault spec silently testing
    nothing is worse than no spec."""
    out: Dict[Tuple[str, str, str], List] = {}
    for clause in filter(None, (c.strip() for c in spec.split(","))):
        try:
            site, point_eq, action = clause.split(":")
            point, _, value = point_eq.partition("=")
            count = 1
            if "@" in action:
                action, _, cnt = action.partition("@")
                count = int(cnt)
            if action not in _ACTIONS or not point or not value:
                raise ValueError(action)
        except ValueError:
            raise ValueError(
                f"bad fault clause {clause!r} — expected "
                "<site>:<point>=<value>:<action>[@<count>] with action in "
                f"{_ACTIONS}") from None
        out[(site, point, value)] = [action, count]
    return out


def _armed() -> Dict[Tuple[str, str, str], List]:
    global _clauses
    if _clauses is None:
        _clauses = parse_spec(_spec_string())
    return _clauses


def active() -> bool:
    return bool(_armed())


def fire(site: str, point: str, value, path: Optional[str] = None) -> None:
    """Fault hook: no-op unless an armed clause matches (site, point,
    value).  ``path`` names the artifact a ``truncate`` action mangles."""
    clauses = _armed()
    if not clauses:
        return
    hit = clauses.get((site, point, str(value)))
    if hit is None or hit[1] <= 0:
        return
    hit[1] -= 1
    action = hit[0]
    log.warning("FAULT INJECTED at %s:%s=%s action=%s path=%s",
                site, point, value, action, path)
    if action == "ioerror":
        raise InjectedFault(
            f"injected IO error at {site}:{point}={value}"
            + (f" ({path})" if path else ""))
    if action == "truncate" and path and os.path.isfile(path):
        size = os.path.getsize(path)
        with open(path, "r+b") as f:
            f.truncate(size // 2)
    # kill (and truncate's tail): a SIGKILL-equivalent hard death — no
    # atexit handlers, no buffered writes, exactly what a preempted VM
    # or OOM-killed container leaves behind
    os.sys.stderr.write(
        f"shifu-tpu: injected hard exit at {site}:{point}={value}\n")
    os.sys.stderr.flush()
    os._exit(137)


def reset_for_tests() -> None:
    """Drop the parsed-spec cache (tests flip the env/property per case)."""
    global _clauses
    _clauses = None
