"""shifu-tpu CLI — the reference's ``shifu`` launcher + ``ShifuCLI``.

Commands mirror reference ``ShifuCLI.java:818-866``:
``new | init | stats | norm | varselect | train | posttrain | eval | export |
test | encode | combo | convert``.  ``-Dkey=value`` properties go to the
Environment tier (reference ``ShifuCLI.java:430-453``).
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from .config import environment


def _split_props(argv: List[str]) -> List[str]:
    """Pull ``-Dk=v`` pairs out of argv into Environment, return the rest."""
    rest = []
    for a in argv:
        if a.startswith("-D") and "=" in a:
            k, _, v = a[2:].partition("=")
            environment.set_property(k, v)
        else:
            rest.append(a)
    return rest


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="shifu-tpu",
        description="TPU-native tabular ML pipeline (new→init→stats→norm→varselect"
                    "→train→posttrain→eval→export)")
    p.add_argument("--dir", default=".", help="model-set directory (default: cwd)")
    p.add_argument("-v", "--verbose", action="store_true")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("new", help="create a new model-set scaffold")
    sp.add_argument("name")
    sp.add_argument("--alg", "-t", default="NN", dest="alg",
                    help="NN|LR|GBT|RF|DT|WDL|SVM (reference `new -t`)")
    sp.add_argument("-m", dest="description", default=None,
                    help="model-set description (reference `new -m`)")

    sp = sub.add_parser("init",
                        help="build initial ColumnConfig.json from header")
    sp.add_argument("-model", dest="init_model", action="store_true",
                    help="fill the algorithm's default train#params into "
                    "ModelConfig.json (reference `init -model`)")

    sp = sub.add_parser("stats", help="per-column stats + binning (+psi/correlation)")
    sp.add_argument("-correlation", "-c", dest="correlation", action="store_true")
    sp.add_argument("-psi", dest="psi", action="store_true")
    sp.add_argument("-rebin", dest="rebin", action="store_true")
    sp.add_argument("-vars", dest="rebin_vars", metavar="A,B",
                    help="rebin only these columns (reference -vars)")
    sp.add_argument("-ivr", dest="rebin_ivr", type=float, default=None,
                    help="rebin IV keep ratio (reference -ivr)")
    sp.add_argument("-bic", dest="rebin_bic", type=int, default=None,
                    help="rebin minimum bin instance count (reference -bic)")

    sp = sub.add_parser("norm", aliases=["normalize", "transform"],
                        help="normalize training data")
    sp.add_argument("-shuffle", dest="shuffle", action="store_true")

    sp = sub.add_parser("varselect", aliases=["varsel"], help="variable selection")
    sp.add_argument("-list", dest="list", action="store_true")
    sp.add_argument("-reset", dest="reset", action="store_true")
    sp.add_argument("-recover", dest="recover", action="store_true")
    sp.add_argument("-recursive", dest="recursive", type=int, default=1,
                    metavar="N", help="SE/ST wrapper rounds: each round "
                    "re-norms + retrains on the current selection, then "
                    "re-scores sensitivity")
    sp.add_argument("-autofilter", dest="autofilter", action="store_true",
                    help="apply only the missing-rate/KS/IV/correlation "
                    "auto filter to the current selection")
    sp.add_argument("-recoverauto", dest="recoverauto", action="store_true",
                    help="restore variables removed by the last -autofilter")

    sp = sub.add_parser("train", help="train model(s)")
    sp.add_argument("-dry", dest="dry", action="store_true")
    sp.add_argument("-shuffle", dest="shuffle", action="store_true")
    sp.add_argument("-resume", dest="resume", action="store_true",
                    help="resume from the latest trainer-state checkpoint")

    sub.add_parser("posttrain", help="bin-average scores + feature importance")

    sp = sub.add_parser("eval", help="evaluate model on eval sets")
    sp.add_argument("-run", dest="run_eval", metavar="EVALSET", nargs="?", const="")
    sp.add_argument("-score", dest="score", metavar="EVALSET", nargs="?", const="")
    sp.add_argument("-nosort", dest="nosort", action="store_true",
                    help="-score: keep input row order (default sorts the "
                    "score file by the selected score column — "
                    "performanceScoreSelector, or the winning class score "
                    "for multi-class; reference `eval -score`)")
    sp.add_argument("-perf", dest="perf", metavar="EVALSET", nargs="?", const="")
    sp.add_argument("-confmat", dest="confmat", metavar="EVALSET", nargs="?", const="")
    sp.add_argument("-norm", dest="norm_eval", metavar="EVALSET", nargs="?",
                    const="")
    sp.add_argument("-new", dest="new_eval", metavar="EVALSET")
    sp.add_argument("-delete", dest="delete_eval", metavar="EVALSET")
    sp.add_argument("-list", dest="list", action="store_true")

    sp = sub.add_parser("export", help="export model "
                        "(pmml|baggingpmml|bagging|columnstats|woemapping|corr)")
    sp.add_argument("type_pos", nargs="?", default=None, metavar="TYPE",
                    help="same as -t (`shifu export pmml`)")
    sp.add_argument("-t", "--type", default="pmml")
    sp.add_argument("-c", dest="concise", action="store_true",
                    help="concise PMML: trim per-bin stats extensions "
                    "(reference `export -c`)")

    sp = sub.add_parser("analysis", help="model spec analysis "
                        "(-fi MODEL: tree feature importance; --telemetry: "
                        "render the last run's span/metric trace; "
                        "--telemetry --timeline OUT: export a Chrome/"
                        "Perfetto trace_event timeline; --telemetry "
                        "--utilization: cost-attribution / roofline "
                        "report)")
    sp.add_argument("-fi", dest="fi_model", metavar="MODELPATH")
    sp.add_argument("-telemetry", "--telemetry", dest="telemetry_report",
                    action="store_true",
                    help="render <modelset>/telemetry/trace.jsonl as a "
                    "per-step span tree with self-time and rows/sec")
    sp.add_argument("-timeline", "--timeline", dest="timeline_out",
                    metavar="OUT.json", default=None,
                    help="with --telemetry: convert the trace to Chrome "
                    "trace_event JSON (load in chrome://tracing or "
                    "ui.perfetto.dev; ingest-thread spans get their own "
                    "track)")
    sp.add_argument("-utilization", "--utilization", dest="utilization",
                    action="store_true",
                    help="with --telemetry: join executable FLOPs/bytes "
                    "(obs cost records) against span wall times — "
                    "achieved FLOP/s, bytes/s, percent-of-peak and a "
                    "roofline verdict per plane (peaks override: "
                    "SHIFU_TPU_PEAK_FLOPS / SHIFU_TPU_PEAK_BW)")
    sp.add_argument("-aggregate", "--aggregate", dest="analysis_aggregate",
                    nargs="+", metavar="DIR", default=None,
                    help="with --telemetry [--timeline]: merge the "
                    "telemetry dirs of N processes (replaces --dir) "
                    "into one report / one trace — per-proc tracks, "
                    "clock-offset normalization from heartbeats, "
                    "per-proc step-lag table")

    sp = sub.add_parser("monitor", help="live health monitor: tail "
                        "<modelset>/telemetry/health/ heartbeats and "
                        "render per-process step/phase/progress with "
                        "staleness flags")
    sp.add_argument("--interval", dest="monitor_interval", type=float,
                    default=2.0, metavar="S",
                    help="seconds between frames (default 2)")
    sp.add_argument("--once", dest="monitor_once", action="store_true",
                    help="render one frame and exit")
    sp.add_argument("--json", dest="monitor_json", action="store_true",
                    help="with --once: print ONE machine-readable JSON "
                    "doc (per-proc health + quorum summary) instead of "
                    "the table; exit 0 healthy, 3 when any process is "
                    "stalled or stale — for CI and cron consumers")
    sp.add_argument("--aggregate", dest="monitor_aggregate", nargs="+",
                    metavar="DIR", default=None,
                    help="merge the health planes of N process telemetry "
                    "dirs (replaces --dir) into one report: tagged "
                    "table, merged quorum, per-proc step-lag table, "
                    "heartbeat clock-offset normalization")

    sp = sub.add_parser("serve", help="online scoring server: the trained "
                        "ensemble AOT-compiled + HBM-pinned behind a "
                        "padded-bucket micro-batcher (knobs: "
                        "-Dshifu.serve.buckets, -Dshifu.serve.maxDelayMs, "
                        "-Dshifu.serve.traceSampleRate per-request "
                        "tracing, -Dshifu.serve.sloP99Ms / "
                        "-Dshifu.serve.sloAvailability SLO objectives; "
                        "GET /slo serves live burn-rate alerts)")
    sp.add_argument("--port", dest="serve_port", type=int, default=8188,
                    help="HTTP port for POST /score + GET /healthz "
                    "(default 8188)")
    sp.add_argument("--max-delay-ms", dest="serve_max_delay_ms",
                    type=float, default=None, metavar="MS",
                    help="deadline flush bound (overrides "
                    "-Dshifu.serve.maxDelayMs; default 2)")
    sp.add_argument("--selfcheck", dest="serve_selfcheck", type=int,
                    nargs="?", const=8, default=0, metavar="N",
                    help="score N synthetic rows in-process and exit "
                    "(no port; CI smoke)")
    sp.add_argument("--replicas", dest="serve_replicas", type=int,
                    default=1, metavar="N",
                    help="fleet mode: spawn N serve workers behind a "
                    "health-/SLO-aware routing front on --port — "
                    "POST /swap coordinates a fleet-wide hot-swap with "
                    "no mixed-model window (knobs: "
                    "-Dshifu.serve.fleetPollMs health-poll cadence, "
                    "-Dshifu.serve.fleetStaleS stale-replica cutoff, "
                    "-Dshifu.serve.canaryFrac canary commit slice)")
    # internal fleet-worker flags (run_fleet passes them when spawning)
    sp.add_argument("--replica", dest="serve_replica", default=None,
                    help=argparse.SUPPRESS)
    sp.add_argument("--announce", dest="serve_announce", default=None,
                    help=argparse.SUPPRESS)

    sp = sub.add_parser("refresh", help="continual refresh: drift-gated "
                        "warm retrain -> AUC-gated hot-swap promotion -> "
                        "SLO-observed probation with automatic rollback "
                        "(knobs: -Dshifu.refresh.psiThreshold, "
                        "-Dshifu.refresh.intervalS, "
                        "-Dshifu.refresh.cooldownS, "
                        "-Dshifu.refresh.minAucDelta, "
                        "-Dshifu.refresh.probationS, "
                        "-Dshifu.refresh.units; one cycle attempt by "
                        "default)")
    sp.add_argument("--daemon", dest="refresh_daemon", action="store_true",
                    help="stay resident: poll the drift artifact / "
                    "schedule forever (the always-on production loop)")
    sp.add_argument("--poll", dest="refresh_poll", type=float,
                    default=2.0, metavar="S",
                    help="seconds between controller ticks (default 2)")

    sp = sub.add_parser("lint", help="AST-based convention checker: "
                        "host-sync/recompile/knob-registry/atomic-write/"
                        "telemetry-guard/manifest rules over shifu_tpu/ "
                        "(exit 0 clean, 2 findings; "
                        "# shifu-lint: disable=RULE suppresses inline; "
                        "lint-baseline.json grandfathers old debt)")
    from .lint.cli import add_lint_args
    add_lint_args(sp)

    sp = sub.add_parser("test", help="pipeline smoke test on a data sample")
    sp.add_argument("-filter", dest="filter_target", nargs="?", const="",
                    default=None, metavar="EVALSET",
                    help="test only the filter expressions: no value = "
                    "training set, '*' = all sets, a name = that eval set")
    sp = sub.add_parser("encode", help="encode dataset by tree-leaf index")
    sp.add_argument("-evalset", dest="evalset", default=None)
    sp.add_argument("-ref", dest="ref_model", default=None, metavar="DIR",
                    help="encode with the tree model of another model-set "
                    "dir (reference ENCODE_REF_MODEL)")

    sp = sub.add_parser("combo", help="multi-algorithm ensemble")
    sp.add_argument("action", choices=["new", "init", "run", "eval"])
    sp.add_argument("-resume", dest="resume", action="store_true",
                    help="skip members already trained")
    sp.add_argument("-alg", dest="algs", default=None,
                    help="colon-separated list, e.g. NN:GBT:LR")

    sp = sub.add_parser("convert", help="convert model spec zip<->binary")
    sp.add_argument("-tozipb", dest="tozipb", action="store_true")
    sp.add_argument("-tob", "-totreeb", dest="tob", action="store_true",
                    help="(reference TO_TREEB)")

    sp = sub.add_parser("save", help="snapshot model-set version")
    sp.add_argument("name", nargs="?", default=None)
    sp = sub.add_parser("switch", help="restore a saved model-set version")
    sp.add_argument("name")
    sub.add_parser("history", help="list saved model-set versions")
    sub.add_parser("show", help="print the current model-set version")
    sp = sub.add_parser("delete", help="delete a saved model-set version")
    sp.add_argument("name")
    sp = sub.add_parser("cp", help="clone this model set's configs into a "
                        "new scaffold dir")
    sp.add_argument("dest")

    # telemetry/profiling knobs on EVERY step (`shifu-tpu train --profile`):
    # --telemetry enables the span/metric trace for this run (same as
    # SHIFU_TPU_TELEMETRY=1); --profile [dir] captures a jax.profiler
    # device timeline per step (same as -Dshifu.profile=dir)
    seen = set()                        # aliases share one parser object
    for name, spx in sub.choices.items():
        if id(spx) in seen:
            continue
        seen.add(id(spx))
        spx.add_argument("--profile", dest="profile_dir", nargs="?",
                         const="profile", default=None, metavar="DIR",
                         help="capture a jax.profiler trace under DIR "
                         "(default ./profile)")
        if name != "analysis":          # analysis --telemetry = the report
            spx.add_argument("--telemetry", dest="telemetry",
                             action="store_true",
                             help="record span/metric telemetry to "
                             "<modelset>/telemetry/trace.jsonl")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    try:
        return _dispatch(argv)
    except Exception as e:
        from .config.errors import ShifuError
        if isinstance(e, ShifuError):
            # coded user errors: message, not traceback (reference ShifuCLI
            # prints ShifuException messages plainly)
            print(str(e), file=sys.stderr)
            return 1
        raise


def _dispatch(argv: Optional[List[str]] = None) -> int:
    argv = _split_props(list(argv if argv is not None else sys.argv[1:]))
    args = build_parser().parse_args(argv)
    from . import configure_logging
    configure_logging(verbose=args.verbose)   # honors SHIFU_TPU_LOG

    if getattr(args, "telemetry", False):
        from . import obs
        obs.set_enabled(True)
    if getattr(args, "profile_dir", None):
        environment.set_property("shifu.profile", args.profile_dir)

    # multi-host bootstrap: no-op unless the launcher set SHIFU_COORDINATOR
    # (one process per host; jax.devices() then spans the fleet)
    from .parallel.mesh import initialize_distributed
    initialize_distributed()

    cmd = args.command
    if cmd == "new":
        from .pipeline.create import create_new_model
        create_new_model(args.name, base_dir=args.dir, algorithm=args.alg,
                         description=args.description)
        return 0
    if cmd == "init":
        if getattr(args, "init_model", False):
            from .pipeline.create import check_algorithm_param
            return check_algorithm_param(args.dir)
        from .pipeline.create import InitProcessor
        return InitProcessor(args.dir).run()
    if cmd == "stats":
        from .pipeline.stats import StatsProcessor
        return StatsProcessor(args.dir, params=vars(args)).run()
    if cmd in ("norm", "normalize", "transform"):
        from .pipeline.norm import NormalizeProcessor
        return NormalizeProcessor(args.dir, params=vars(args)).run()
    if cmd in ("varselect", "varsel"):
        from .pipeline.varselect import VarSelectProcessor
        return VarSelectProcessor(args.dir, params=vars(args)).run()
    if cmd == "train":
        from .pipeline.train import TrainProcessor
        return TrainProcessor(args.dir, params=vars(args)).run()
    if cmd == "posttrain":
        from .pipeline.posttrain import PostTrainProcessor
        return PostTrainProcessor(args.dir, params=vars(args)).run()
    if cmd == "eval":
        from .pipeline.evaluate import EvalProcessor
        return EvalProcessor(args.dir, params=vars(args)).run()
    if cmd == "export":
        from .pipeline.export import ExportProcessor
        if getattr(args, "type_pos", None):
            args.type = args.type_pos
        return ExportProcessor(args.dir, params=vars(args)).run()
    if cmd == "analysis":
        if getattr(args, "telemetry_report", False) \
                or getattr(args, "utilization", False):
            agg = getattr(args, "analysis_aggregate", None)
            if getattr(args, "utilization", False):
                from .obs.utilization import render_utilization
                print(render_utilization(args.dir))
                return 0
            if getattr(args, "timeline_out", None):
                from .obs.report import NO_TELEMETRY_HINT
                from .obs.timeline import (export_merged_timeline,
                                           export_timeline)
                skipped: list = []
                if agg:
                    out = export_merged_timeline(agg, args.timeline_out,
                                                 skipped=skipped)
                else:
                    out = export_timeline(args.dir, args.timeline_out,
                                          skipped=skipped)
                if out is None:
                    print(NO_TELEMETRY_HINT)
                else:
                    print(f"timeline -> {out}  (load in chrome://tracing "
                          "or https://ui.perfetto.dev)")
                    if skipped:
                        print(f"warning: {len(skipped)} torn trace "
                              "line(s) skipped (crashed run mid-write?)")
                return 0
            if agg:
                from .obs.report import render_telemetry_merged
                print(render_telemetry_merged(agg))
                return 0
            from .obs.report import render_telemetry
            print(render_telemetry(args.dir))
            return 0
        from .pipeline.analysis import analyze_model_fi
        return analyze_model_fi(args.fi_model)
    if cmd == "monitor":
        from .obs.monitor import run_monitor
        return run_monitor(args.dir, interval_s=args.monitor_interval,
                           once=args.monitor_once,
                           json_mode=getattr(args, "monitor_json", False),
                           aggregate_dirs=getattr(args,
                                                  "monitor_aggregate",
                                                  None))
    if cmd == "serve":
        if getattr(args, "serve_replicas", 1) > 1:
            from .serve.router import run_fleet
            return run_fleet(args.dir, replicas=args.serve_replicas,
                             port=args.serve_port,
                             max_delay_ms=args.serve_max_delay_ms)
        from .serve.server import run_serve
        return run_serve(args.dir, port=args.serve_port,
                         selfcheck=args.serve_selfcheck,
                         max_delay_ms=args.serve_max_delay_ms,
                         replica=getattr(args, "serve_replica", None),
                         announce=getattr(args, "serve_announce", None))
    if cmd == "refresh":
        from .pipeline.refresh import RefreshProcessor
        return RefreshProcessor(args.dir, params={
            "daemon": getattr(args, "refresh_daemon", False),
            "poll": getattr(args, "refresh_poll", 2.0)}).run()
    if cmd == "lint":
        from .lint.cli import run_lint_cli
        return run_lint_cli(args)
    if cmd == "test":
        from .pipeline.smoke import SmokeTestProcessor
        return SmokeTestProcessor(args.dir, params=vars(args)).run()
    if cmd == "encode":
        from .pipeline.encode import EncodeProcessor
        return EncodeProcessor(args.dir, params=vars(args)).run()
    if cmd == "combo":
        from .pipeline.combo import run_combo
        return run_combo(args.dir, args.action, args.algs,
                         resume=getattr(args, "resume", False))
    if cmd == "convert":
        from .pipeline.convert import run_convert
        return run_convert(args.dir, vars(args))
    if cmd == "save":
        from .pipeline.manage import save_version
        return save_version(args.dir, args.name)
    if cmd == "show":
        from .pipeline.manage import show_current
        return show_current(args.dir)
    if cmd == "delete":
        from .pipeline.manage import delete_version
        return delete_version(args.dir, args.name)
    if cmd == "cp":
        from .pipeline.manage import copy_model_set
        return copy_model_set(args.dir, args.dest)
    if cmd == "switch":
        from .pipeline.manage import switch_version
        return switch_version(args.dir, args.name)
    if cmd == "history":
        from .pipeline.manage import show_history
        return show_history(args.dir)
    raise SystemExit(f"unknown command {cmd}")


if __name__ == "__main__":
    raise SystemExit(main())
