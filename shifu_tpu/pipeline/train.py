"""`train` step — reference ``TrainModelProcessor.java:105`` re-imagined.

Loads the materialized norm (NN/LR/WDL) or cleaned-binned (GBT/RF) shards,
expands grid-search trials, builds bagging/k-fold row-weight matrices, and
runs the vmapped SPMD ensemble trainer.  The reference's N-YARN-job fan-out
(``runDistributedTrain``, ``:661-1029``) becomes ensemble members on the mesh;
progress lines replace the HDFS progress file + TailThread (``:1862``);
per-N-epoch tmp models land in ``models/tmp`` like ``NNOutput.postIteration``.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .. import faults
from ..config.model_config import Algorithm
from ..config.validator import ModelStep
from ..data.shards import Shards
from ..models import nn as nn_model
from ..train import grid_search
from ..train.nn_trainer import TrainSettings, train_ensemble
from ..train.sampling import member_masks
from .processor import BasicProcessor

log = logging.getLogger(__name__)


def settings_from_params(params: Dict[str, Any], train_conf,
                         defaults: Optional[Dict[str, Any]] = None) -> TrainSettings:
    """Map reference ``train#params`` keys (``GridSearch``-compatible names:
    Propagation/LearningRate/RegularizedConstant/DropoutRate/...) onto
    TrainSettings."""
    p = dict(defaults or {})
    p.update(params or {})
    return TrainSettings(
        optimizer=str(p.get("Propagation", p.get("Optimizer", "R"))),
        learning_rate=float(p.get("LearningRate", 0.1)),
        learning_decay=float(p.get("LearningDecay", 0.0)),
        l2=float(p.get("RegularizedConstant", p.get("L2Const", 0.0))),
        l1=float(p.get("L1Const", 0.0)),
        dropout_rate=float(p.get("DropoutRate", 0.0)),
        epochs=int(train_conf.numTrainEpochs),
        batch_size=int(p.get("MiniBatchs", 0) or 0),
        early_stop_window=int(p.get("WindowSize", 10)
                              if train_conf.earlyStopEnable else 0),
        weight_initializer=str(p.get("WeightInitializer", "xavier")),
        seed=int(p.get("Seed", 0)),
        tmp_model_every=int(p.get("TmpModelEpochs", 0) or 0),
        checkpoint_every=int(p.get("CheckpointInterval", 25)),
        fixed_layers=tuple(int(v) for v in p.get("FixedLayers", []) or []),
        fixed_bias=bool(p.get("FixedBias", False)),
        matmul_precision=str(p.get("Precision", "") or ""),
        # training-precision ladder (f32 | bf16 | mixed); "" defers to
        # the -Dshifu.train.precision property, default f32
        precision=str(p.get("TrainPrecision", "") or ""),
    )


def nn_spec_from_params(input_dim: int, params: Dict[str, Any],
                        column_nums: List[int],
                        feature_names: List[str]) -> nn_model.NNModelSpec:
    """Reference NN shape keys: NumHiddenLayers / NumHiddenNodes /
    ActivationFunc (``NNMaster``/``DTrainUtils`` param names)."""
    nodes = params.get("NumHiddenNodes", [50])
    acts = params.get("ActivationFunc", ["tanh"] * len(nodes))
    n_layers = int(params.get("NumHiddenLayers", len(nodes)))
    nodes = [int(v) for v in nodes][:n_layers] or [50]
    acts = [str(a).lower() for a in acts][:n_layers] or ["tanh"]
    while len(acts) < len(nodes):
        acts.append(acts[-1])
    return nn_model.NNModelSpec(
        input_dim=input_dim, hidden_nodes=nodes, activations=acts,
        output_dim=1, output_activation="sigmoid",
        loss=str(params.get("Loss", "squared")).lower(),
        column_nums=column_nums, feature_names=feature_names)


def lr_spec(input_dim: int, params: Dict[str, Any], column_nums: List[int],
            feature_names: List[str]) -> nn_model.NNModelSpec:
    """LR as the degenerate 0-hidden-layer net: one sigmoid(xW+b) matmul —
    exactly ``LogisticRegressionWorker.java:302-346``'s model."""
    return nn_model.NNModelSpec(
        input_dim=input_dim, hidden_nodes=[], activations=[],
        output_dim=1, output_activation="sigmoid", loss="log",
        column_nums=column_nums, feature_names=feature_names,
        extra={"algorithm": "LR"})


def svm_spec(input_dim: int, params: Dict[str, Any], column_nums: List[int],
             feature_names: List[str]) -> nn_model.NNModelSpec:
    """Linear SVM: hinge loss on a linear head (reference
    ``core/alg/SVMTrainer.java`` Kernel/Gamma/Const params).  Nonlinear
    kernels (rbf/poly/sigmoid) train through the kernel-matrix dual solver
    (``train/svm_trainer.py``) and never reach this spec — except in
    STREAMED mode, where the kernel matrix cannot be materialized
    (coded error; the reference's libsvm SVM is local-only too).
    ``Const`` (the C penalty) maps to L2 ``1/(2C)`` on the weights — the
    textbook soft-margin objective scaled by C."""
    kernel = str(params.get("Kernel", "linear")).lower()
    if kernel != "linear":
        from ..config.errors import ErrorCode, ShifuError
        raise ShifuError(ErrorCode.ERROR_MODELCONFIG_NOT_VALIDATION,
                         f"SVM Kernel={kernel!r} cannot run in streamed/"
                         "out-of-core mode (the kernel matrix is "
                         "local-scale by nature); drop "
                         "-Dshifu.train.streaming or use NN/GBT")
    c_penalty = float(params.get("Const", 1.0))
    return nn_model.NNModelSpec(
        input_dim=input_dim, hidden_nodes=[], activations=[],
        output_dim=1, output_activation="linear", loss="hinge",
        column_nums=column_nums, feature_names=feature_names,
        extra={"algorithm": "SVM", "svm_const": c_penalty})


def _apply_svm_objective(settings, alg: Algorithm,
                         run_params: Dict[str, Any]) -> None:
    """Soft-margin C -> L2 1/(2C), default C=1.0 (svm_spec docstring) —
    the ONE place the SVM objective maps onto TrainSettings."""
    if alg == Algorithm.SVM:
        settings.l2 = 1.0 / (2.0 * float(run_params.get("Const", 1.0)))


class TrainProcessor(BasicProcessor):
    step = ModelStep.TRAIN

    def process(self) -> int:
        mc = self.model_config
        alg = mc.train.algorithm
        if self.params.get("dry"):
            log.info("dry run: algorithm=%s bags=%d epochs=%d", alg.name,
                     mc.train.baggingNum, mc.train.numTrainEpochs)
            return 0
        if self.journal.was_torn and not self.params.get("resume"):
            # the previous train died mid-step (journal never committed):
            # auto-resume from the trainer-state checkpoints — exactly
            # what an explicit `train -resume` would do; with no
            # checkpoint on disk the trainers fall back to fresh init
            log.info("train: previous run was interrupted — resuming "
                     "from trainer checkpoints")
            self.params["resume"] = True
        if alg in (Algorithm.NN, Algorithm.LR, Algorithm.SVM,
                   Algorithm.TENSORFLOW):
            # TENSORFLOW: the reference bridges to TF-on-YARN
            # (TrainModelProcessor.java:395-449); tpu-native IS the bridge —
            # the same net trains as the jitted NN path
            if alg == Algorithm.TENSORFLOW:
                # the probe step enforces this too; the direct-API path
                # (callers constructing TrainProcessor without probe)
                # must hit the same coded wall, not a silent remap
                from ..config.meta import tf_ignored_param_problems
                from ..config.validator import ValidationError
                tf_problems = tf_ignored_param_problems(mc.train)
                if tf_problems:
                    raise ValidationError(tf_problems)
                log.info("algorithm TENSORFLOW: training the same network "
                         "on the native jitted NN path (documented "
                         "deviation — no TF interop; the reference's "
                         "TF-on-YARN bridge role is served by XLA)")
            return self._train_nn_family(
                Algorithm.NN if alg == Algorithm.TENSORFLOW else alg)
        if alg in (Algorithm.GBT, Algorithm.RF, Algorithm.DT):
            from ..train.dt_trainer import run_tree_training
            return run_tree_training(self)
        if alg == Algorithm.WDL:
            from ..train.wdl_trainer import run_wdl_training
            return run_wdl_training(self)
        raise ValueError(f"unsupported algorithm {alg}")


    def _trials(self, params: Dict[str, Any]) -> List[Dict[str, Any]]:
        """Grid trials: explicit per-line file (train.gridConfigFile,
        validated per trial via the meta schema) or cartesian expansion of
        list-valued params; file trials inherit unlisted keys from
        train#params."""
        gcf = self.model_config.train.gridConfigFile
        if gcf:
            file_trials = grid_search.load_grid_config(self._abs(gcf))
            # list-valued params are grid axes in their own right — expand
            # them first so a file trial that doesn't mention the key
            # doesn't inherit a raw list (cartesian product of both)
            base_trials = grid_search.expand(params) \
                if grid_search.is_grid_search(params) else [params]
            merged = [{**b, **t} for b in base_trials for t in file_trials]
            # a file trial that sets an expanded key collapses that axis —
            # drop the resulting exact duplicates (keep first occurrence)
            seen, trials = set(), []
            for t in merged:
                key = tuple(sorted((k, repr(v)) for k, v in t.items()))
                if key not in seen:
                    seen.add(key)
                    trials.append(t)
            from ..config.meta import validate_train_params
            problems = []
            for i, t in enumerate(trials):
                for p in validate_train_params(
                        t, self.model_config.train.algorithm):
                    problems.append(f"gridConfigFile trial {i + 1}: {p}")
            if problems:
                from ..config.validator import ValidationError
                raise ValidationError(problems)
            return trials
        if grid_search.is_grid_search(params):
            return grid_search.expand(params)
        return [params]

    # ------------------------------------------------------------ NN / LR
    def _train_nn_family(self, alg: Algorithm) -> int:
        from ..config.model_config import MultipleClassification
        mc = self.model_config
        K = len(mc.dataSet.posTags) if mc.is_multi_class() else 0
        ova = K > 2 and mc.train.multiClassifyMethod == \
            MultipleClassification.ONEVSALL
        if ova and (mc.train.gridConfigFile or
                    grid_search.is_grid_search(mc.train.params or {})):
            # ONE guard for both the in-RAM and streamed paths
            raise ValueError("grid search is not supported with "
                             "ONEVSALL multi-class")
        shards = self._open_shards(self.paths.norm_dir)
        if self._use_streaming(shards, shards.schema):
            return self._train_nn_streamed(alg, shards, n_classes=K,
                                           ova=ova)
        with self.phase("load_data"):
            data = shards.load_all()
        x, y, w = data["x"], data["y"], data["w"]
        if self.params.get("shuffle"):
            # reference `train -shuffle` re-randomizes row order before
            # training (MapReduceShuffle re-run)
            perm = np.random.default_rng(0).permutation(len(y))
            x, y, w = x[perm], y[perm], w[perm]
        schema = shards.schema
        column_nums = schema.get("columnNums", [])
        feature_names = schema.get("outputNames", [])
        n, d = x.shape
        log.info("train %s: %d rows x %d features", alg.name, n, d)

        if alg == Algorithm.SVM and str((mc.train.params or {}).get(
                "Kernel", "linear")).lower() != "linear":
            # nonlinear kernels leave the shared NN machinery: the
            # reference's libsvm C-SVC becomes an MXU kernel-matrix dual
            # solve (train/svm_trainer.py)
            return self._train_kernel_svm(x, y, w, column_nums,
                                          feature_names)

        params = dict(mc.train.params or {})
        trials = self._trials(params)
        is_gs = len(trials) > 1
        kfold = mc.train.numKFold if mc.train.isCrossValidation else -1
        bags = 1 if is_gs else max(1, mc.train.baggingNum)

        os.makedirs(self.paths.tmp_models_dir, exist_ok=True)
        progress_path = self.paths.progress_path
        t0 = time.time()

        results = []
        # live progress stream, tailed by operators; a torn tail is
        # tolerated and resume replays it from the journal (PR 4)
        with open(progress_path, "w") as pf:  # shifu-lint: disable=atomic-write
            # grid trials group by structural shape: same-shape trials train
            # as ONE vmapped run with per-member hyper arrays; non-grid =
            # one run with all bagging members vmapped together
            runs = grid_search.stackable_groups(trials) if is_gs \
                else [list(range(bags))]
            for run in runs:
                run_params = trials[run[0]] if is_gs else dict(params)
                spec = self._make_spec(alg, d, run_params, column_nums,
                                       feature_names)
                if K > 2 and not ova:
                    # NATIVE multiclass: one softmax head over K classes
                    spec.output_dim = K
                    spec.output_activation = "softmax"
                    spec.extra["n_classes"] = K
                settings = settings_from_params(run_params, mc.train)
                _apply_svm_objective(settings, alg, run_params)
                if not is_gs:
                    # trainer-state fail-over checkpoints (grid trials are
                    # cheap; only full runs checkpoint/resume)
                    settings.checkpoint_dir = self.paths.checkpoint_dir
                    settings.resume = bool(self.params.get("resume"))
                    # refresh warm-start: N MORE epochs past the
                    # restored state (plain resume keeps the budget)
                    settings.resume_extra = int(
                        self.params.get("refresh_extra") or 0)
                run_kfold = kfold if not is_gs else -1
                up_w = mc.train.upSampleWeight
                if K > 2 and up_w != 1.0:
                    # up-sampling is a binary notion (reference restricts it
                    # to regression/binary); class indices would skew
                    # arbitrary classes
                    log.warning("upSampleWeight ignored for multi-class")
                    up_w = 1.0
                train_w, valid_w = member_masks(
                    n, 1 if is_gs else bags,
                    valid_rate=mc.train.validSetRate,
                    kfold=run_kfold,
                    sample_rate=mc.train.baggingSampleRate,
                    replacement=mc.train.baggingWithReplacement,
                    stratified=mc.train.stratifiedSample,
                    up_sample_weight=up_w,
                    targets=y, seed=settings.seed)
                if is_gs:
                    # every trial in the group sees the SAME split — they
                    # must differ only in hypers, never in data draw
                    train_w = np.tile(train_w, (len(run), 1))
                    valid_w = np.tile(valid_w, (len(run), 1))
                y_members = None
                if ova:
                    # fan each bagging member out per class: member b*K+k
                    # trains class k's binary task on bag b's mask
                    b0 = train_w.shape[0]
                    train_w = np.repeat(train_w, K, axis=0)
                    valid_w = np.repeat(valid_w, K, axis=0)
                    y_members = np.tile(
                        np.stack([(y == k).astype(np.float32)
                                  for k in range(K)]), (b0, 1))
                    spec.extra.update({"ova_classes": K, "n_classes": K})
                n_members = train_w.shape[0]  # kfold mode yields numKFold
                train_w = train_w * w[None, :]
                valid_w = valid_w * w[None, :]
                init_list = self._continuous_init(spec, n_members, alg,
                                                  settings)

                member_hypers = None
                if is_gs and len(run) > 1:
                    # the group's trials differ only in stackable scalars —
                    # feed them as per-member arrays, one compiled run;
                    # identical init so the comparison isolates the hypers
                    if init_list is None:
                        import jax
                        p0 = nn_model.init_params(
                            jax.random.PRNGKey(settings.seed), spec,
                            settings.weight_initializer)
                        init_list = [p0] * len(run)
                    else:
                        # continuous warm-start: every trial resumes from
                        # the SAME saved model, not one bagged model each
                        init_list = [init_list[0]] * len(run)
                    tsl = [settings_from_params(trials[t], mc.train)
                           for t in run]
                    base_lr = settings.learning_rate
                    member_hypers = {
                        "lr_scale": np.array([s.learning_rate / base_lr
                                              for s in tsl]),
                        "l2": np.array([s.l2 for s in tsl]),
                        "l1": np.array([s.l1 for s in tsl]),
                        "dropout": np.array([s.dropout_rate for s in tsl]),
                    }
                with self.phase("train"):
                    res = train_ensemble(
                        x, y, train_w, valid_w, spec, settings,
                        init_params_list=init_list,
                        progress=self._progress_fn(pf, run),
                        checkpoint=self._checkpoint_fn(spec, alg),
                        y_members=y_members,
                        member_hypers=member_hypers)
                results.append((run, spec, res,
                                [trials[t] for t in run] if is_gs
                                else run_params))

        with self.phase("save_models"):
            self._write_models(results, alg, is_gs)
        log.info("train done in %.1fs", time.time() - t0)
        return 0

    # -------------------------------------------------------- streaming
    def _train_kernel_svm(self, x, y, w, column_nums, feature_names) -> int:
        """Nonlinear-kernel SVM bags (reference ``SVMTrainer.java``
        Kernel/Gamma/Const; local-scale by design — see
        ``train/svm_trainer.py`` for the dual formulation)."""
        from ..models.svm import SVMModelSpec, save_model
        from ..train.svm_trainer import train_kernel_svm
        from ..train.sampling import member_masks

        mc = self.model_config
        params = dict(mc.train.params or {})
        if grid_search.is_grid_search(params):
            raise ValueError("grid search is not supported for kernel SVM "
                             "(single local-scale solve per bag)")
        n, d = x.shape
        kernel = str(params.get("Kernel", "linear")).lower()
        kernel = {"radialbasisfunction": "rbf"}.get(kernel, kernel)
        spec = SVMModelSpec(
            input_dim=d, kernel=kernel,
            gamma=float(params.get("Gamma", 1.0 / max(d, 1))),
            coef0=float(params.get("Coef0", 0.0)),
            degree=int(params.get("Degree", 3)),
            column_nums=column_nums, feature_names=feature_names,
            extra={"algorithm": "SVM"})
        c_penalty = float(params.get("Const", 1.0))
        bags = max(1, mc.train.baggingNum)
        os.makedirs(self.paths.models_dir, exist_ok=True)
        # per-bag commit hooks: each solved bag journals its model, so an
        # interrupted multi-bag run resumes at the first unsolved bag
        # (the kernel SVM's "epoch" is the whole dual solve)
        items = self.journal.arm({"alg": "SVM", "kernel": spec.kernel,
                                  "const": c_penalty, "bags": bags},
                                 resume=bool(self.params.get("resume")))
        with open(self.paths.progress_path, "a" if items else "w") as pf:
            for b in range(bags):
                path = os.path.join(self.paths.models_dir, f"model{b}.svm")
                if items.get(f"bag-{b}"):
                    log.info("svm bag %d: already solved, skipping", b)
                    continue
                faults.fire("train", "bag", b, path=path)
                tw, _ = member_masks(
                    n, 1, valid_rate=mc.train.validSetRate,
                    sample_rate=mc.train.baggingSampleRate,
                    replacement=mc.train.baggingWithReplacement,
                    targets=y, seed=b)
                train_mask = (tw[0] > 0) & (w > 0)
                sv_x, alpha_y, tr, va, n_sv = train_kernel_svm(
                    x, y, train_mask, spec, c_penalty)
                save_model(path, spec, sv_x, alpha_y)
                self.journal.commit_item(f"bag-{b}", files=[path],
                                         valid_err=float(va))
                pf.write(f"Trainer #{b} Train Error: {tr:.6f} "
                         f"Validation Error: {va:.6f} ({n_sv} SVs)\n")
                log.info("svm bag %d: %d SVs -> %s", b, n_sv, path)
        return 0

    def _open_shards(self, directory: str) -> Shards:
        """The step's view of the materialized plane.  The refresh loop
        passes ``window_cursor`` (rows earlier trainings consumed) so a
        warm retrain streams only the NEW data windows — shard-aligned,
        see :meth:`Shards.from_row`."""
        shards = Shards.open(directory)
        cur = int(self.params.get("window_cursor") or 0)
        if cur:
            view = shards.from_row(cur)
            log.info("data-window cursor %d: training on %d of %d rows "
                     "(%d of %d shards)", cur, view.num_rows,
                     shards.num_rows, view.n_shards, shards.n_shards)
            return view
        return shards

    def _use_streaming(self, shards: Shards, schema: dict) -> bool:
        """Out-of-core mode when the materialized data exceeds the memory
        budget (reference ``guagua.data.memoryFraction`` role) or when
        forced via ``-Dshifu.train.streaming=on`` — the shared
        :func:`data.streaming.should_stream` decision (varselect's
        sensitivity/genetic planes consult the same one)."""
        from ..data.streaming import should_stream
        return should_stream(shards, schema)

    def _train_nn_streamed(self, alg: Algorithm, shards: Shards,
                           n_classes: int = 0, ova: bool = False) -> int:
        """Streamed counterpart of the in-RAM branch: windows flow through
        ``train_ensemble_streamed``; sampling masks are stateless hashes of
        the global row index (``data.streaming``)."""
        from ..config import environment
        from ..data.streaming import (ShardStream, mask_fn_from_settings,
                                      stream_window_rows)
        from ..parallel.mesh import device_mesh
        from ..train.nn_trainer import train_ensemble_streamed

        mc = self.model_config
        schema = shards.schema
        column_nums = schema.get("columnNums", [])
        feature_names = schema.get("outputNames", [])
        d = len(feature_names)
        n_rows = schema.get("numRows") or shards.num_rows

        params = dict(mc.train.params or {})
        trials = self._trials(params)
        is_gs = len(trials) > 1
        kfold = mc.train.numKFold if mc.train.isCrossValidation else -1
        bags = 1 if is_gs else max(1, mc.train.baggingNum)
        if mc.train.stratifiedSample:
            log.warning("streaming: stratified validation degrades to "
                        "Bernoulli split (needs a global pass)")
        if self.params.get("shuffle"):
            log.warning("streaming: `train -shuffle` ignored; use "
                        "`norm -shuffle` to reshuffle the materialized shards")

        K = n_classes if ova else 0
        # members on the ensemble axis: k-fold overrides bagging count;
        # OVA fans each bag out per class (member b*K + k trains class k,
        # the in-RAM y_members convention)
        mesh_members = kfold if (not is_gs and kfold and kfold > 1) else bags
        if ova:
            mesh_members = mesh_members * K
        mesh = device_mesh(n_ensemble=mesh_members)
        data_size = mesh.shape["data"]
        window_rows = stream_window_rows(4 * (d + 2), data_size, shards)
        log.info("train %s STREAMED: %d rows x %d features, window %d rows",
                 alg.name, n_rows, d, window_rows)

        # elastic multi-controller mode (-Dshifu.dcn.elastic + a stable
        # SHIFU_PROCESS_ID): the cross-process combine rides the quorum
        # step protocol instead of the in-mesh psum — grid-search trials
        # keep the synchronous path (their step namespaces would collide)
        ectx = None
        if not is_gs:
            from ..parallel.elastic import elastic_context_for
            ectx = elastic_context_for(self.dir, step_name="TRAIN")
            if ectx is not None:
                ectx.start()

        os.makedirs(self.paths.tmp_models_dir, exist_ok=True)
        t0 = time.time()
        results = []
        with open(self.paths.progress_path, "w") as pf:  # shifu-lint: disable=atomic-write
            runs = [[t] for t in range(len(trials))] if is_gs \
                else [list(range(bags))]
            for run in runs:
                run_params = trials[run[0]] if is_gs else dict(params)
                spec = self._make_spec(alg, d, run_params, column_nums,
                                       feature_names)
                if n_classes > 2 and not ova:
                    spec.output_dim = n_classes
                    spec.output_activation = "softmax"
                    spec.extra["n_classes"] = n_classes
                if ova:
                    spec.extra.update({"ova_classes": K, "n_classes": K})
                settings = settings_from_params(run_params, mc.train)
                _apply_svm_objective(settings, alg, run_params)
                if not is_gs:
                    settings.checkpoint_dir = self.paths.checkpoint_dir
                    settings.resume = bool(self.params.get("resume"))
                    settings.resume_extra = int(
                        self.params.get("refresh_extra") or 0)
                run_kfold = kfold if not is_gs else -1
                n_members = run_kfold if (run_kfold and run_kfold > 1) \
                    else (len(run) if is_gs else bags)
                up_w = mc.train.upSampleWeight
                if n_classes > 2 and up_w != 1.0:
                    log.warning("upSampleWeight ignored for multi-class")
                    up_w = 1.0
                mask_fn = mask_fn_from_settings(
                    n_members, valid_rate=mc.train.validSetRate,
                    kfold=run_kfold,
                    sample_rate=mc.train.baggingSampleRate,
                    replacement=mc.train.baggingWithReplacement,
                    up_sample_weight=up_w,
                    seed=settings.seed)
                member_classes = None
                if ova:
                    # repeat each bag's masks per class; member b*K + k
                    # binarizes class k ON DEVICE in the streamed trainer.
                    # The K host copies of each bag mask cost K*4B/row vs
                    # the window's d*4B/row feature transfer — a few
                    # percent for typical K; indexing base masks on
                    # device (m // K) would remove it if K grows
                    base_fn, b0 = mask_fn, n_members
                    def mask_fn(idx, targets, base_fn=base_fn):
                        tm, vm = base_fn(idx, targets)
                        return (np.repeat(tm, K, axis=0),
                                np.repeat(vm, K, axis=0))
                    member_classes = [k for _ in range(b0)
                                      for k in range(K)]
                    n_members = b0 * K
                # full-batch streams take the shape-stable remainder
                # ladder (tail window shrinks instead of padding to W);
                # the minibatch mode slices windows by fixed W-derived
                # edges, so it keeps the uniform shape
                stream = ShardStream(
                    shards, ("x", "y", "w"), window_rows,
                    remainder_multiple=data_size
                    if settings.batch_size == 0 else 0)
                init_list = self._continuous_init(spec, n_members, alg,
                                                  settings)
                run_elastic = ectx
                if ectx is not None and settings.batch_size != 0:
                    log.warning("elastic mode needs full-batch streaming "
                                "(MiniBatchs=0); this run stays "
                                "synchronous")
                    run_elastic = None
                try:
                    res = train_ensemble_streamed(
                        stream, spec, settings, n_members, mask_fn,
                        init_params_list=init_list,
                        progress=self._progress_fn(pf, run),
                        checkpoint=self._checkpoint_fn(spec, alg),
                        mesh=mesh, member_classes=member_classes,
                        elastic=run_elastic)
                except BaseException:
                    if ectx is not None:
                        ectx.stop(exit_code=1)
                        ectx = None
                    raise
                results.append((run, spec, res, run_params))
        if ectx is not None:
            ectx.stop(exit_code=0)

        self._write_models(results, alg, is_gs)
        log.info("train done in %.1fs (streamed)", time.time() - t0)
        return 0

    # ---------------------------------------------------- shared run setup
    def _make_spec(self, alg: Algorithm, d: int, run_params: Dict[str, Any],
                   column_nums, feature_names):
        if alg == Algorithm.SVM:
            return svm_spec(d, run_params, column_nums, feature_names)
        if alg == Algorithm.LR:
            return lr_spec(d, run_params, column_nums, feature_names)
        return nn_spec_from_params(d, run_params, column_nums, feature_names)

    def _progress_fn(self, pf, run):
        def progress(epoch, tr, va):
            line = (f"Trial {run} Epoch #{epoch + 1} "
                    f"Train Error: {tr:.6f} Validation Error: {va:.6f}")
            pf.write(line + "\n")
            pf.flush()
            faults.fire("train", "epoch", epoch + 1)
            log.info(line)
        return progress

    def _checkpoint_fn(self, spec, alg: Algorithm):
        def checkpoint(epoch, params_list):
            for i, p in enumerate(params_list):
                path = self.paths.tmp_model_path(i, epoch + 1,
                                                 alg.name.lower())
                nn_model.save_model(path, spec, p)
        return checkpoint

    def _continuous_init(self, spec, n_members: int, alg: Algorithm,
                         settings=None):
        """Continuous training: warm-start members from existing final
        models; a GROWN configuration fits the saved net into the larger
        structure (reference ``NNMaster.java:331-362,605-645``)."""
        if not self.model_config.train.isContinuous:
            return None
        import jax
        seed = settings.seed if settings else 0
        initializer = settings.weight_initializer if settings else "xavier"
        ext = alg.name.lower()
        init = []
        grown = 0
        for i in range(n_members):
            path = self.paths.model_path(i, ext)
            if not os.path.isfile(path):
                return None
            old_spec, params = nn_model.load_model(path)
            if old_spec.layer_dims() != spec.layer_dims():
                params = nn_model.fit_params_into(
                    old_spec, params, spec,
                    jax.random.fold_in(jax.random.PRNGKey(seed), i),
                    initializer)
                if params is None:
                    log.warning("continuous: model%d does not embed in the "
                                "new structure, fresh init", i)
                    return None
                grown += 1
            init.append(params)
        log.info("continuous training: warm-started %d members%s", n_members,
                 f" ({grown} grown via structure fit-in)" if grown else "")
        return init

    @staticmethod
    def _scoring_spec(spec):
        """The SPEC a model file ships with: SVM trains on a linear head
        (hinge needs raw margins) but scores through sigmoid so eval stays
        in the documented [0, 1]*1000 range — monotone, rank metrics
        unchanged."""
        if (spec.extra or {}).get("algorithm") == "SVM":
            import dataclasses
            return dataclasses.replace(
                spec, output_activation="sigmoid",
                extra={**spec.extra, "margin_sigmoid": True})
        return spec

    def _write_models(self, results, alg: Algorithm, is_gs: bool) -> None:
        ext = alg.name.lower()
        os.makedirs(self.paths.models_dir, exist_ok=True)
        # clear stale models from previous runs (fewer bags / other algs) so
        # eval's glob never mixes ensembles
        for f in os.listdir(self.paths.models_dir):
            if f.startswith("model"):
                os.remove(os.path.join(self.paths.models_dir, f))
        if is_gs:
            # grid search: pick the best trial by validation error
            # (reference re-trains the winner; our members ARE full runs)
            flat = []
            for run, spec, res, run_params in results:
                for j, trial_idx in enumerate(run):
                    tp = run_params[j] if isinstance(run_params, list) \
                        else run_params
                    flat.append((res.valid_errors[j], trial_idx, spec,
                                 res.params[j], tp))
            from ..train.grid_search import rank_and_report
            by_idx = {t[1]: t for t in flat}
            idxs = sorted(by_idx)
            order = rank_and_report(
                self.paths.tmp_dir, [by_idx[i][0] for i in idxs],
                [by_idx[i][4] for i in idxs])
            best = by_idx[idxs[order[0]]]
            log.info("grid search: best trial #%d valid error %.6f params %s",
                     best[1], best[0], best[4])
            nn_model.save_model(self.paths.model_path(0, ext),
                                self._scoring_spec(best[2]), best[3])
            return
        run, spec, res, _ = results[0]
        ova_k = (spec.extra or {}).get("ova_classes")
        for i, p in enumerate(res.params):
            sp = spec
            if ova_k:
                # member b*K+k scores class k — stamp the class identity
                import dataclasses
                sp = dataclasses.replace(
                    spec, extra={**spec.extra, "class_index": i % ova_k})
            nn_model.save_model(self.paths.model_path(i, ext),
                                self._scoring_spec(sp), p)
        log.info("saved %d model(s); valid errors %s", len(res.params),
                 np.round(res.valid_errors, 6).tolist())
