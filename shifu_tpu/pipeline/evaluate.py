"""`eval` step — reference ``EvalModelProcessor.java:67,159`` without the
cluster: eval-set CRUD + streaming scoring + confusion/performance report.

The reference submits ``Eval.pig``/``EvalScore.pig`` (``:424-436``) whose
mappers run ``EvalScoreUDF`` → ``ModelRunner`` per record with Hadoop
counters; here each eval set streams through the same ModelRunner batched on
device, and the counter totals fall out of the sweep.  Outputs mirror
``PathFinder``: EvalScore tsv, EvalConfusionMatrix csv,
EvalPerformance.json, gain-chart csv.

The reference's optional Spark eval engine (an external-jar launcher that
moved the same scoring onto a Spark cluster) is SUBSUMED rather than
ported: its one role — spreading scoring over cluster cores — is served
by the mesh-sharded scorer (rows shard over every chip, see ``_run``) at
~40x the 100-worker cluster's measured rate on one chip; there is no
external engine to launch.
"""

from __future__ import annotations

import csv
import json
import logging
import os
import time
from typing import List, Optional

import numpy as np

from .. import ioutil, obs
from ..config.model_config import EvalConfig, RawSourceData
from ..config.validator import ModelStep
from ..data import DataSource
from ..data.parsepool import iter_extracted
from ..eval.metrics import evaluate_scores, gain_chart_rows
from ..eval.scorer import ModelRunner, Scorer
from .processor import BasicProcessor

log = logging.getLogger(__name__)


class EvalProcessor(BasicProcessor):
    step = ModelStep.EVAL

    def process(self) -> int:
        p = self.params
        if p.get("list"):
            for ev in self.model_config.evals:
                log.info("eval set: %s (%s)", ev.name, ev.dataSet.dataPath)
            return 0
        if p.get("new_eval"):
            return self._new_eval(p["new_eval"])
        if p.get("delete_eval"):
            return self._delete_eval(p["delete_eval"])
        if p.get("norm_eval") is not None:
            return self._norm_export(p["norm_eval"] or None)
        for key in ("run_eval", "score", "perf", "confmat"):
            if p.get(key) is not None:
                return self._run(p[key] or None, action=key)
        # bare `eval` = run all sets (reference default)
        return self._run(None, action="run_eval")

    def _norm_export(self, name: Optional[str]) -> int:
        """`eval -norm`: write the eval set's NORMALIZED feature matrix
        (reference ``EvalModelProcessor`` runNormalize path — feeds external
        scoring/debug tooling the exact model inputs)."""
        from ..data.transform import DatasetTransformer
        for i in self._eval_sets(name):
            ev = self.model_config.evals[i]
            tf = DatasetTransformer(self.model_config, self.column_configs,
                                    for_eval_set=i)
            ds = ev.dataSet
            source = DataSource(self._abs(ds.dataPath), ds.dataDelimiter,
                                header_path=self._abs(ds.headerPath),
                                header_delimiter=ds.headerDelimiter)
            out = self.paths.eval_norm_path(ev.name)
            os.makedirs(os.path.dirname(out), exist_ok=True)
            n_rows = 0
            with ioutil.atomic_open(out, newline="") as f:
                w = csv.writer(f, delimiter="|")
                header_written = False
                for _ci, ex in iter_extracted(
                        source, tf.extractor,
                        cache_root=self.paths.raw_cache_dir):
                    tc = tf.transform_extracted(ex)
                    if tc.n == 0:
                        continue
                    if not header_written:
                        w.writerow(["tag", "weight"] + list(tf.output_names))
                        header_written = True
                    block = np.column_stack(
                        [tc.target.astype(int).astype(str),
                         tc.weight.astype(str)]
                        + [np.char.mod("%.6f", tc.x[:, j])
                           for j in range(tc.x.shape[1])])
                    w.writerows(block.tolist())
                    n_rows += tc.n
            log.info("eval %s: normalized %d rows -> %s", ev.name, n_rows,
                     out)
        return 0

    # -------------------------------------------------------------- CRUD
    def _new_eval(self, name: str) -> int:
        if any(e.name == name for e in self.model_config.evals):
            log.error("eval set %s already exists", name)
            return 1
        ev = EvalConfig(name=name, dataSet=RawSourceData())
        # inherit the training source as the template (reference copies
        # dataSet section on `eval -new`)
        base = self.model_config.dataSet
        for f in ("dataPath", "dataDelimiter", "headerPath", "headerDelimiter",
                  "targetColumnName", "posTags", "negTags", "missingOrInvalidValues",
                  "weightColumnName"):
            v = getattr(base, f)
            setattr(ev.dataSet, f, list(v) if isinstance(v, list) else v)
        self.model_config.evals.append(ev)
        self.save_model_config()
        log.info("created eval set %s", name)
        return 0

    def _delete_eval(self, name: str) -> int:
        before = len(self.model_config.evals)
        self.model_config.evals = [e for e in self.model_config.evals
                                   if e.name != name]
        if len(self.model_config.evals) == before:
            log.error("no eval set named %s", name)
            return 1
        self.save_model_config()
        return 0

    # --------------------------------------------------------------- run
    def _eval_sets(self, name: Optional[str]) -> List[int]:
        evals = self.model_config.evals
        if name:
            idx = [i for i, e in enumerate(evals) if e.name == name]
            if not idx:
                raise ValueError(f"no eval set named {name}")
            return idx
        return list(range(len(evals)))

    def _run(self, name: Optional[str], action: str) -> int:
        from ..parallel.mesh import device_mesh
        # rows shard across every chip during scoring (the reference's
        # cluster eval, ``EvalModelProcessor.java:424-436``)
        scorer = Scorer.from_dir(self.paths.models_dir,
                                 mesh=device_mesh())  # load models once
        rc = 0
        for i in self._eval_sets(name):
            rc |= self._run_one(i, action, scorer)
        return rc

    def _run_one(self, idx: int, action: str, scorer: Scorer) -> int:
        mc = self.model_config
        if mc.is_multi_class() and len(mc.dataSet.posTags) > 2:
            return self._run_one_multiclass(idx, action, scorer)
        ev = mc.evals[idx]
        runner = ModelRunner(mc, self.column_configs, scorer.models,
                             for_eval_set=idx, mesh=scorer.mesh)
        ds = ev.dataSet
        source = DataSource(self._abs(ds.dataPath), ds.dataDelimiter,
                            header_path=self._abs(ds.headerPath),
                            header_delimiter=ds.headerDelimiter)
        eval_dir = self.paths.eval_dir(ev.name)
        os.makedirs(eval_dir, exist_ok=True)

        sel = ev.performanceScoreSelector or "mean"
        all_scores, all_targets, all_weights = [], [], []
        score_path = self.paths.eval_score_path(ev.name)
        n_models = len(scorer.models)
        # streaming drift monitor: the eval set is the LIVE distribution —
        # its binned windows accumulate per-column PSI against the
        # training-time snapshot (None / zero-cost when telemetry is off)
        drift = obs.start_drift_monitor(runner.transformer.columns)
        score_t0 = time.perf_counter()
        with self.phase(f"score:{ev.name}") as ph, \
                ioutil.atomic_open(score_path, newline="") as sf:
            w = csv.writer(sf, delimiter="|")
            w.writerow(["tag", "weight", "mean", "max", "min", "median"]
                       + [f"model{i}" for i in range(n_models)])
            for _ci, ex in iter_extracted(
                    source, runner.transformer.extractor,
                    cache_root=self.paths.raw_cache_dir):
                out = runner.compute(ex)
                if out["n"] == 0:
                    continue
                if drift is not None:
                    drift.update(out["bins"])
                res = out["result"]
                chosen = res.select(sel)
                all_scores.append(chosen)
                all_targets.append(out["target"])
                all_weights.append(out["weight"])
                # vectorized row formatting — the scoring is batched, the
                # writing must not be the hot loop
                block = np.column_stack(
                    [out["target"].astype(int).astype(str),
                     out["weight"].astype(str)]
                    + [np.char.mod("%.3f", col) for col in
                       (res.mean, res.max, res.min, res.median)]
                    + [np.char.mod("%.3f", res.scores[:, m])
                       for m in range(n_models)])
                w.writerows(block.tolist())
            ph.set(rows=int(sum(len(s) for s in all_scores)))
        if not all_scores:
            log.error("eval %s: no records scored", ev.name)
            return 1
        scores = np.concatenate(all_scores)
        targets = np.concatenate(all_targets)
        weights = np.concatenate(all_weights)
        obs.counter("eval.rows_scored").inc(len(scores))
        obs.gauge("eval.rows_per_sec").set(
            len(scores) / max(time.perf_counter() - score_t0, 1e-9))
        obs.event("eval_set", eval_set=ev.name, rows=len(scores),
                  models=n_models, action=action)
        if drift is not None:
            drift.emit(path=self.paths.drift_path)
        log.info("eval %s: scored %d records (%d pos / %d neg) with %d model(s)",
                 ev.name, len(scores), int(targets.sum()),
                 int((1 - targets).sum()), n_models)
        if action == "score":
            # reference `eval -score` sorts the score file by model score
            # for review unless -nosort (EvalModelProcessor NOSORT; the
            # cluster version runs an ORDER BY job)
            if not self.params.get("nosort"):
                with open(score_path) as f:
                    header = f.readline()
                    rows = f.readlines()
                order = np.argsort(-scores, kind="stable")
                with ioutil.atomic_open(score_path) as f:
                    f.write(header)
                    f.writelines(rows[i] for i in order)
            return 0

        # host sweep by choice: the per-row score CSV above already forced
        # the scores to the host, and re-uploading them to sweep on device
        # costs more than the host argsort on this link (~5 MB/s up).  The
        # device plane (metrics.sweep_device / Scorer.score_device) serves
        # callers whose scores are HBM-resident.
        from ..eval.metrics import evaluate_curves, sweep
        curves = sweep(scores, targets, weights)   # ONE sort; two consumers
        result = evaluate_curves(curves, buckets=ev.performanceBucketNum)
        result.modelCount = n_models
        from ..ioutil import atomic_write_json
        atomic_write_json(self.paths.eval_performance_path(ev.name),
                          result.to_dict())
        self._write_confusion(ev.name, result)
        self._write_gains(eval_dir, result)
        from ..eval.report import html_report
        ioutil.atomic_write_text(os.path.join(eval_dir, "report.html"),
                                 html_report(ev.name, curves, result))
        obs.gauge(f"eval.{ev.name}.auc").set(result.areaUnderRoc)
        obs.gauge(f"eval.{ev.name}.pr_auc").set(result.areaUnderPr)
        # training-time quality baseline: score distribution + AUC the
        # serve-path quality monitor (obs/quality) judges live traffic
        # against — last eval run wins, matching the serving artifacts
        from ..obs.quality import write_posttrain_snapshot
        write_posttrain_snapshot(self.paths.posttrain_snapshot_path,
                                 scores, auc=result.areaUnderRoc)
        log.info("eval %s: AUC %.6f weighted AUC %.6f PR-AUC %.6f",
                 ev.name, result.areaUnderRoc, result.weightedAuc,
                 result.areaUnderPr)
        return 0

    def _run_one_multiclass(self, idx: int, action: str,
                            scorer: Scorer) -> int:
        """Multi-class eval: [n, K] class scores, argmax predicted tag,
        accuracy + per-class OvR AUC + K x K confusion (reference
        ``MultiClsTagPredictor`` + ``EvalScoreUDF`` multi-class columns)."""
        from ..eval.metrics import evaluate_multiclass
        mc = self.model_config
        ev = mc.evals[idx]
        runner = ModelRunner(mc, self.column_configs, scorer.models,
                             for_eval_set=idx, mesh=scorer.mesh)
        ds = ev.dataSet
        source = DataSource(self._abs(ds.dataPath), ds.dataDelimiter,
                            header_path=self._abs(ds.headerPath),
                            header_delimiter=ds.headerDelimiter)
        eval_dir = self.paths.eval_dir(ev.name)
        os.makedirs(eval_dir, exist_ok=True)
        # the SAME tag resolution ChunkExtractor uses: eval-set tags first —
        # class indices in targets are positions in THIS list
        tags = list(ds.posTags or mc.dataSet.posTags)
        k_models = scorer.n_classes()
        if k_models and len(tags) != k_models:
            raise ValueError(
                f"eval set {ev.name} lists {len(tags)} tags but the models "
                f"were trained over {k_models} classes — tag lists must "
                "match in length and order")
        all_cs, all_t, all_w = [], [], []
        with ioutil.atomic_open(self.paths.eval_score_path(ev.name),
                                newline="") as sf:
            w = csv.writer(sf, delimiter="|")
            w.writerow(["tag", "weight", "predictedTag"]
                       + [f"score_{t}" for t in tags])
            for _ci, ex in iter_extracted(
                    source, runner.transformer.extractor,
                    cache_root=self.paths.raw_cache_dir):
                out = runner.compute_classes(ex)
                if out["n"] == 0:
                    continue
                cs = out["class_scores"]
                pred = cs.argmax(axis=1)
                tag_arr = np.asarray(tags, dtype=object)
                block = np.column_stack(
                    [out["target"].astype(int).astype(str),
                     out["weight"].astype(str),
                     tag_arr[pred].astype(str)]
                    + [np.char.mod("%.6f", cs[:, k])
                       for k in range(cs.shape[1])])
                w.writerows(block.tolist())
                all_cs.append(cs)
                all_t.append(out["target"])
                all_w.append(out["weight"])
        if not all_cs:
            log.error("eval %s: no records scored", ev.name)
            return 1
        cs = np.concatenate(all_cs)
        t = np.concatenate(all_t)
        wgt = np.concatenate(all_w)
        log.info("eval %s: scored %d records over %d classes with %d "
                 "model(s)", ev.name, len(t), len(tags), len(scorer.models))
        if action == "score":
            if not self.params.get("nosort"):
                # same default as the binary path: sorted for review,
                # multiclass keyed by the winning class's score
                path = self.paths.eval_score_path(ev.name)
                with open(path) as f:
                    header = f.readline()
                    rows = f.readlines()
                order = np.argsort(-cs.max(axis=1), kind="stable")
                with ioutil.atomic_open(path) as f:
                    f.write(header)
                    f.writelines(rows[i] for i in order)
            return 0
        rep = evaluate_multiclass(cs, t, wgt)
        rep["tags"] = tags
        from ..ioutil import atomic_write_json
        atomic_write_json(self.paths.eval_performance_path(ev.name), rep)
        log.info("eval %s: accuracy %.6f macro OvR AUC %.6f", ev.name,
                 rep["accuracy"], rep["macroAuc"])
        return 0

    def _write_confusion(self, name: str, result) -> None:
        path = self.paths.eval_confusion_path(name)
        with ioutil.atomic_open(path, newline="") as f:
            w = csv.writer(f)
            cols = ["binLowestScore", "tp", "fp", "fn", "tn", "precision",
                    "recall", "fpr", "actionRate", "liftUnit", "weightedTp",
                    "weightedFp", "weightedFn", "weightedTn",
                    "weightedPrecision", "weightedRecall", "weightedFpr"]
            w.writerow(cols)
            for pt in result.points:
                w.writerow([getattr(pt, c) for c in cols])

    def _write_gains(self, eval_dir: str, result) -> None:
        with ioutil.atomic_open(os.path.join(eval_dir, "gainchart.csv"),
                                newline="") as f:
            rows = gain_chart_rows(result)
            if not rows:
                return
            w = csv.DictWriter(f, fieldnames=list(rows[0]))
            w.writeheader()
            w.writerows(rows)



# ---------------------------------------------------------- parity oracle
def score_records_offline(model_set_dir: str, records,
                          selector: str = "mean") -> np.ndarray:
    """Raw JSON records through the OFFLINE norm + score pipeline.

    This is the parity oracle for raw-record serving: the fused transform
    inside ``serve.AOTScorer`` (``POST /score`` with ``records``) must
    reproduce these float32 scores BIT-identically — same stringification
    (:func:`data.reader.record_field_str`), same ``parse_numeric`` missing
    grammar, same ``NormalizedColumn``/``ColumnBinner`` math, same
    ensemble reduction.  tests/test_serve.py drives both paths over the
    same records and asserts byte equality.
    """
    import pandas as pd

    from ..config import ModelConfig, load_column_configs
    from ..data.reader import RawChunk, record_field_str
    from ..data.transform import DatasetTransformer

    mc = ModelConfig.load(os.path.join(model_set_dir, "ModelConfig.json"))
    ccs = load_column_configs(os.path.join(model_set_dir,
                                           "ColumnConfig.json"))
    tf = DatasetTransformer(mc, ccs)
    names = [c.columnName for c in tf.columns]
    data = pd.DataFrame(
        {n: [record_field_str(r.get(n)) for r in records] for n in names},
        dtype=object)
    tc = tf.transform(RawChunk(columns=names, data=data))
    scorer = Scorer.from_dir(os.path.join(model_set_dir, "models"))
    res = scorer.score(tc.x, bins=tc.bins)
    return np.asarray(res.select(selector), np.float32)
