"""`posttrain` step — reference ``PostTrainModelProcessor.java`` +
``core/posttrain/PostTrainMapper.java``: score the training data with the
final models and write per-(column, bin) average scores into
``ColumnConfig.binAvgScore``, plus a feature-importance ranking.

The reference runs an MR job over raw data; here the cleaned binned matrix
and the norm matrix are already materialized, so it is one streamed
scatter-mean on device-scored batches.  Feature importance for NN/LR models
is the per-column score spread (max bin avg − min bin avg, weighted by bin
population) — tree models get split-gain FI from their own trainer.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List

import numpy as np

from .. import ioutil
from ..config.validator import ModelStep
from ..data.shards import Shards
from ..eval.scorer import Scorer
from .processor import BasicProcessor

log = logging.getLogger(__name__)


class PostTrainProcessor(BasicProcessor):
    step = ModelStep.POSTTRAIN

    def process(self) -> int:
        scorer = Scorer.from_dir(self.paths.models_dir)
        norm = Shards.open(self.paths.norm_dir)
        clean = Shards.open(self.paths.clean_dir)
        col_nums: List[int] = clean.schema.get("columnNums", [])
        by_num = {c.columnNum: c for c in self.column_configs}

        sums: Dict[int, np.ndarray] = {}
        counts: Dict[int, np.ndarray] = {}
        for nshard, cshard in zip(norm.iter_shards(), clean.iter_shards()):
            bins = cshard["bins"]
            scores = scorer.score(nshard["x"], bins=bins.astype(np.int32)).mean
            for j, cnum in enumerate(col_nums):
                cc = by_num.get(cnum)
                if cc is None:
                    continue
                nb = cc.num_bins() + 1  # + missing bin
                b = bins[:, j].astype(np.int64)
                b = np.clip(b, 0, nb - 1)
                s = np.bincount(b, weights=scores, minlength=nb)
                c = np.bincount(b, minlength=nb)
                if cnum not in sums:
                    sums[cnum], counts[cnum] = s, c.astype(np.float64)
                else:
                    sums[cnum] += s
                    counts[cnum] += c

        fi: Dict[str, float] = {}
        for cnum in col_nums:
            cc = by_num.get(cnum)
            if cc is None or cnum not in sums:
                continue
            avg = sums[cnum] / np.maximum(counts[cnum], 1)
            cc.columnBinning.binAvgScore = [int(round(v)) for v in avg]
            pop = counts[cnum] / max(counts[cnum].sum(), 1)
            seen = counts[cnum] > 0
            if seen.any():
                spread = float(avg[seen].max() - avg[seen].min())
                fi[cc.columnName] = spread * float(1 - pop.max())
        self.save_column_configs()

        os.makedirs(self.paths.post_train_dir, exist_ok=True)
        ranked = sorted(fi.items(), key=lambda kv: -kv[1])
        with ioutil.atomic_open(self.paths.feature_importance_path) as f:
            for name, v in ranked:
                f.write(f"{name}\t{v:.4f}\n")
        with ioutil.atomic_open(self.paths.bin_avg_score_path) as f:
            for cnum in col_nums:
                cc = by_num.get(cnum)
                if cc and cc.columnBinning.binAvgScore:
                    f.write(f"{cnum}|{cc.columnName}|"
                            + ",".join(map(str, cc.columnBinning.binAvgScore))
                            + "\n")
        log.info("posttrain: bin avg scores for %d columns; top features: %s",
                 len(sums), [n for n, _ in ranked[:5]])
        return 0
