"""`encode` step — reference ``ModelDataEncodeProcessor.java``: re-emit a
dataset with each row encoded as the tree-leaf index per tree of a trained
forest (feature crosses for downstream linear models).
"""

from __future__ import annotations

import logging
import os

import numpy as np

import jax.numpy as jnp

from .. import ioutil
from ..config.validator import ModelStep
from ..data import DataSource
from ..data.transform import DatasetTransformer
from ..models import load_any
from ..ops.tree import traverse_nodes
from .processor import BasicProcessor

log = logging.getLogger(__name__)


def leaf_indices(trees, bins: np.ndarray) -> np.ndarray:
    """[n, n_trees] terminal-node id per tree (same traversal as predict,
    returning the node instead of its value)."""
    b = jnp.asarray(bins, jnp.int32)
    cols = [np.asarray(traverse_nodes(jnp.asarray(t.split_feat),
                                      jnp.asarray(t.left_mask), b, t.depth))
            for t in trees]
    return np.stack(cols, axis=1)


class EncodeProcessor(BasicProcessor):
    step = ModelStep.EVAL

    @property
    def profile_name(self) -> str:
        return "ENCODE"

    def process(self) -> int:
        mc = self.model_config
        ref = self.params.get("ref_model")
        if ref:
            # `encode -ref <dir>`: leaf-encode with ANOTHER model set's
            # trained tree model (reference ENCODE_REF_MODEL — champion
            # model crosses for stacking)
            from ..config import ModelConfig
            from ..config.path_finder import PathFinder
            ref_cfg = os.path.join(ref, "ModelConfig.json")
            if not os.path.isfile(ref_cfg):
                log.error("-ref %s is not a model-set dir (no "
                          "ModelConfig.json)", ref)
                return 1
            ref_mc = ModelConfig.load(ref_cfg)
            model_path = PathFinder(ref_mc, ref).model_path(0, None)
        else:
            model_path = self.paths.model_path(0, None)
        if not os.path.isfile(model_path):
            log.error("no model at %s — encode needs a trained GBT/RF",
                      model_path)
            return 1
        model = load_any(model_path)
        if getattr(model, "input_kind", "norm") != "bins":
            log.error("encode requires a tree model (GBT/RF); found %s",
                      type(model).__name__)
            return 1
        if ref:
            # the model's split_feat/bin ids index THIS set's clean plane:
            # a ref model trained on a different column selection or
            # binning would emit silent garbage — require exact layout
            # agreement, per column (reference stacking assumes a shared
            # ColumnConfig)
            from ..config.column_config import load_column_configs
            from ..data.transform import model_input_columns
            ours = [c.columnNum for c in
                    model_input_columns(mc, self.column_configs)]
            want = list(model.spec.column_nums or [])
            if want and want != ours:
                log.error("-ref model was trained on columns %s but this "
                          "set's model inputs are %s — encode needs the "
                          "same ColumnConfig selection/order", want, ours)
                return 1
            ref_cc_path = os.path.join(ref, "ColumnConfig.json")
            if os.path.isfile(ref_cc_path):
                ref_bins = {c.columnNum: c.num_bins()
                            for c in load_column_configs(ref_cc_path)}
                mine = {c.columnNum: c.num_bins()
                        for c in self.column_configs}
                bad = [cn for cn in (want or ours)
                       if ref_bins.get(cn) != mine.get(cn)]
                if bad:
                    log.error("-ref model's binning disagrees on columns "
                              "%s (per-column bin counts differ) — re-run "
                              "stats/norm with matching binning", bad)
                    return 1

        evalset = self.params.get("evalset")
        if evalset:
            idx = [i for i, e in enumerate(mc.evals) if e.name == evalset]
            if not idx:
                log.error("no eval set named %s", evalset)
                return 1
            ds = mc.evals[idx[0]].dataSet
            transformer = DatasetTransformer(mc, self.column_configs,
                                             for_eval_set=idx[0])
            out_name = f"EncodedData.{evalset}"
        else:
            ds = mc.dataSet
            transformer = DatasetTransformer(mc, self.column_configs)
            out_name = "EncodedData"

        source = DataSource(self._abs(ds.dataPath), ds.dataDelimiter,
                            header_path=self._abs(ds.headerPath),
                            header_delimiter=ds.headerDelimiter)
        out_path = os.path.join(self.paths.tmp_dir, out_name)
        n = 0
        with ioutil.atomic_open(out_path) as f:
            f.write("target|" + "|".join(
                f"tree{t}" for t in range(len(model.trees))) + "\n")
            for chunk in source.iter_chunks():
                tc = transformer.transform(chunk)
                if tc.n == 0:
                    continue
                leaves = leaf_indices(model.trees, tc.bins)
                block = np.column_stack(
                    [tc.target.astype(int).astype(str),
                     *(leaves[:, t].astype(str)
                       for t in range(leaves.shape[1]))])
                f.write("\n".join("|".join(r) for r in block.tolist()) + "\n")
                n += tc.n
        log.info("encoded %d rows x %d trees -> %s", n, len(model.trees),
                 out_path)
        return 0
