"""`analysis` command — standalone model-spec analysis (reference
``ShifuCLI.java:658`` ``analysisModelFi``): feature importance from a saved
GBT/RF model file, written next to it as ``<model>.fi``.

The compact forest format serializes splits and leaves but not per-node
gains, so the standalone FI is depth-weighted split frequency (a split at
level L counts 1/2^L — shallower splits partition more rows); the exact
gain-weighted FI is produced at train time (``tmp/feature_importance.json``).
"""

from __future__ import annotations

import logging
import os

import numpy as np

from .. import ioutil

log = logging.getLogger(__name__)


def analyze_model_fi(model_path: str) -> int:
    if not model_path or not os.path.isfile(model_path):
        log.error("model %s does not exist", model_path)
        return 1
    ext = os.path.splitext(model_path)[1].lower()
    if ext not in (".gbt", ".rf", ".dt"):
        log.error("analysis -fi needs a GBT/RF model, got %s", model_path)
        return 1
    from ..models import tree as tree_model
    spec, trees = tree_model.load_model(model_path)
    n_feat = len(spec.column_nums or [])
    if not n_feat:
        n_feat = int(max(int(t.split_feat.max()) for t in trees)) + 1
    fi = np.zeros(n_feat)
    for t in trees:
        sf = np.asarray(t.split_feat)
        nodes = np.flatnonzero(sf >= 0)
        levels = np.floor(np.log2(nodes + 1)).astype(int)
        np.add.at(fi, sf[nodes], 1.0 / (1 << levels))
    names = spec.feature_names or [str(cn) for cn in spec.column_nums
                                   or range(n_feat)]
    out = model_path + ".fi"
    order = np.argsort(-fi)
    with ioutil.atomic_open(out) as f:
        for j in order:
            f.write(f"{names[j]}\t{fi[j]:.6f}\n")
    log.info("feature importance (%d features, %d trees) -> %s",
             n_feat, len(trees), out)
    return 0
