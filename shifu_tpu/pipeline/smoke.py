"""`test` step — reference ``ShifuTestProcessor.java``: user-side smoke test
that configs, filters and tag mapping parse cleanly on a sample of records
before burning cluster (here: device) time.
"""

from __future__ import annotations

import logging

import numpy as np

from ..config.validator import ModelStep
from ..data import DataSource
from ..data.extract import ChunkExtractor
from .processor import BasicProcessor

log = logging.getLogger(__name__)

SAMPLE_ROWS = 100_000


class SmokeTestProcessor(BasicProcessor):
    step = ModelStep.INIT  # validates at init level; runs pre-stats fine

    def process(self) -> int:
        mc = self.model_config
        # reference ShifuTestProcessor.java:54-60 `-filter [target]`:
        # blank = training set only, "*" = train + every eval set,
        # a name = that eval set only; default (no -filter) tests all
        target = self.params.get("filter_target")
        # four cases: None / "*" = training + all evals; "" = training
        # only; "a,b" = the named eval sets (comma-split, like the
        # reference's per-name loop)
        if target in (None, "*"):
            names = None
        elif str(target).strip() == "":
            return self._test_source("training", mc.dataSet, for_eval=None)
        else:
            names = [t.strip() for t in str(target).split(",") if t.strip()]
            if not names:                       # e.g. "," — a typo, not blank
                log.error("test -filter %r: no eval set names given", target)
                return 1
        rc = 0
        if names is None:
            rc |= self._test_source("training", mc.dataSet, for_eval=None)
        unmatched = set(names or [])
        for i, ev in enumerate(mc.evals):
            if names is not None and ev.name not in names:
                continue
            if ev.dataSet.dataPath:
                unmatched.discard(ev.name)
                rc |= self._test_source(f"eval:{ev.name}", ev.dataSet,
                                        for_eval=i)
        if unmatched:
            log.error("test -filter %s: no such eval set (or it has no "
                      "dataPath): %s", target, sorted(unmatched))
            return 1
        return rc

    def _test_source(self, label, ds, for_eval) -> int:
        try:
            source = DataSource(self._abs(ds.dataPath), ds.dataDelimiter,
                                header_path=self._abs(ds.headerPath),
                                header_delimiter=ds.headerDelimiter)
            extractor = ChunkExtractor(self.model_config, self.column_configs,
                                       for_eval_set=for_eval)
        except Exception as e:
            log.error("%s: FAILED to open (%s)", label, e)
            return 1
        n = pos = neg = filtered = 0
        missing_cells = 0
        for chunk in source.iter_chunks():
            ex = extractor.extract(chunk)
            raw_n = len(chunk.data)
            filtered += raw_n - ex.n
            n += ex.n
            pos += int(ex.target.sum())
            neg += int((1 - ex.target).sum())
            missing_cells += int((~ex.numeric_valid).sum())
            if n >= SAMPLE_ROWS:
                break
        if n == 0:
            log.error("%s: 0 usable records (check tags/filters/delimiter)",
                      label)
            return 1
        if pos == 0 or neg == 0:
            log.error("%s: one-sided tags (%d pos / %d neg) — check "
                      "posTags/negTags", label, pos, neg)
            return 1
        log.info("%s: OK — %d records sampled (%d pos / %d neg, %d filtered, "
                 "%.2f%% missing numeric cells)", label, n, pos, neg, filtered,
                 100.0 * missing_cells / max(n * max(ex.numeric.shape[1], 1), 1))
        return 0
