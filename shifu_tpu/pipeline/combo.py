"""`combo` step — reference ``ComboModelProcessor.java``: multi-algorithm
ensemble.  ``combo new -alg NN:GBT:LR`` records the member algorithms;
``combo run`` trains one sub-model set per algorithm (sharing the parent's
stats/ColumnConfig); ``combo eval`` scores every member on the eval sets and
reports the assembled (mean) performance.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
from typing import List, Optional

import numpy as np

from .. import ioutil

log = logging.getLogger(__name__)

COMBO_FILE = "combo.json"


def run_combo(model_set_dir: str, action: str, algs: Optional[str],
              resume: bool = False) -> int:
    d = os.path.abspath(model_set_dir)
    if action == "new":
        if not algs:
            log.error("combo new requires -alg A:B:C")
            return 1
        members = [a.strip().upper() for a in algs.split(":") if a.strip()]
        ioutil.atomic_write_json(os.path.join(d, COMBO_FILE),
                                 {"algorithms": members})
        log.info("combo: %s", members)
        return 0

    combo_path = os.path.join(d, COMBO_FILE)
    if not os.path.isfile(combo_path):
        log.error("no %s — run `combo new -alg ...` first", COMBO_FILE)
        return 1
    members: List[str] = json.load(open(combo_path))["algorithms"]

    if action == "init":
        return _init_members(d, members)
    if action == "run":
        rc = _init_members(d, members)
        if rc:
            return rc
        return _train_members(d, members, resume=resume)
    if action == "eval":
        return _eval_members(d, members)
    log.error("unknown combo action %s", action)
    return 1


def _member_dir(d: str, alg: str, i: int) -> str:
    return os.path.join(d, f"combo_{i}_{alg}")


def _init_members(d: str, members: List[str]) -> int:
    """Each member = a sub model-set dir sharing the parent's configs/stats
    but with its own train.algorithm (reference sub-model dirs)."""
    from ..config import ModelConfig
    from ..config.meta import unknown_param_problems
    from ..config.validator import ValidationError
    parent = ModelConfig.load(os.path.join(d, "ModelConfig.json"))
    # typos must fail HERE — the per-member applicability filter below would
    # otherwise silently drop them (the parent dict legitimately mixes keys
    # of several algorithm families, so only unknown keys are errors)
    bad = unknown_param_problems(parent.train.params)
    if bad:
        raise ValidationError(bad)
    for i, alg in enumerate(members):
        md = _member_dir(d, alg, i)
        os.makedirs(md, exist_ok=True)
        mc = ModelConfig.load(os.path.join(d, "ModelConfig.json"))
        from ..config.model_config import Algorithm
        mc.train.algorithm = Algorithm[alg]
        mc.basic.name = f"{mc.basic.name}_{alg}{i}"
        # keep only the params applicable to this member's algorithm —
        # driven by the meta schema so combo and probe() can't disagree
        from ..config.meta import TRAIN_PARAM_RULES
        mc.train.params = {
            k: v for k, v in (mc.train.params or {}).items()
            if (r := TRAIN_PARAM_RULES.get(k)) is not None
            and (r.algs is None or alg in r.algs)}
        if alg not in ("NN", "LR", "SVM", "TENSORFLOW"):
            # tree/WDL members can't grid-search — inheriting the parent's
            # grid file or list-valued axes would hard-fail their training
            # step; those members fall back to per-key defaults
            mc.train.gridConfigFile = None
            from ..train.grid_search import _is_axis
            mc.train.params = {k: v for k, v in mc.train.params.items()
                               if not (isinstance(v, list)
                                       and _is_axis(k, v))}
        elif mc.train.gridConfigFile and \
                not os.path.isabs(mc.train.gridConfigFile):
            # member configs resolve paths against THEIR dir — pin the
            # parent-relative grid file to the parent
            mc.train.gridConfigFile = os.path.join(
                d, mc.train.gridConfigFile)
        mc.save(os.path.join(md, "ModelConfig.json"))
        shutil.copy(os.path.join(d, "ColumnConfig.json"),
                    os.path.join(md, "ColumnConfig.json"))
    log.info("combo init: %d member dirs", len(members))
    return 0


def _train_members(d: str, members: List[str], resume: bool = False) -> int:
    """``combo run [-resume]``: -resume skips members whose model file is
    already on disk (reference ComboModelProcessor -resume)."""
    from ..eval.scorer import discover_model_paths
    from .norm import NormalizeProcessor
    from .train import TrainProcessor
    for i, alg in enumerate(members):
        md = _member_dir(d, alg, i)
        if resume and discover_model_paths(os.path.join(md, "models")):
            log.info("combo: member %d (%s) already trained, skipping "
                     "(-resume)", i, alg)
            continue
        log.info("combo: training member %d (%s)", i, alg)
        rc = NormalizeProcessor(md, params={}).run()
        if rc == 0:
            rc = TrainProcessor(md, params={}).run()
        if rc:
            log.error("combo member %d (%s) failed", i, alg)
            return rc
    return 0


def _eval_members(d: str, members: List[str]) -> int:
    """Score each member on the parent's eval sets; assemble by mean
    (reference assembles sub-model scores into a combined score column)."""
    from ..config import ModelConfig, load_column_configs
    from ..data import DataSource
    from ..eval.metrics import evaluate_scores
    from ..eval.scorer import ModelRunner, Scorer

    mc = ModelConfig.load(os.path.join(d, "ModelConfig.json"))
    ccs = load_column_configs(os.path.join(d, "ColumnConfig.json"))
    rc = 0
    for ei, ev in enumerate(mc.evals):
        ds = ev.dataSet
        if not ds.dataPath:
            continue
        member_scores = []
        targets = weights = None
        for i, alg in enumerate(members):
            md = _member_dir(d, alg, i)
            scorer = Scorer.from_dir(os.path.join(md, "models"))
            runner = ModelRunner(mc, ccs, scorer.models, for_eval_set=ei)
            path = ds.dataPath if os.path.isabs(ds.dataPath) else \
                os.path.normpath(os.path.join(d, ds.dataPath))
            source = DataSource(path, ds.dataDelimiter)
            s_parts, t_parts, w_parts = [], [], []
            for chunk in source.iter_chunks():
                out = runner.compute(chunk)
                if out["n"] == 0:
                    continue
                s_parts.append(out["result"].mean)
                t_parts.append(out["target"])
                w_parts.append(out["weight"])
            if not s_parts:
                log.error("combo eval %s: no usable rows (check tags/filter) "
                          "— skipping", ev.name)
                rc = 1
                member_scores = []
                break
            member_scores.append(np.concatenate(s_parts))
            if targets is None:
                targets = np.concatenate(t_parts)
                weights = np.concatenate(w_parts)
        if not member_scores:
            continue
        assembled = np.mean(np.stack(member_scores), axis=0)
        res = evaluate_scores(assembled, targets, weights,
                              buckets=ev.performanceBucketNum)
        out_path = os.path.join(d, f"ComboEval.{ev.name}.json")
        doc = res.to_dict()
        doc["members"] = members
        per_member = []
        for i, (alg, ms) in enumerate(zip(members, member_scores)):
            m_res = evaluate_scores(ms, targets, weights)
            per_member.append({"member": f"{i}:{alg}",
                               "areaUnderRoc": m_res.to_dict()["areaUnderRoc"]})
        doc["memberAuc"] = per_member
        ioutil.atomic_write_json(out_path, doc)
        log.info("combo eval %s: assembled AUC %.6f (members: %s)", ev.name,
                 res.areaUnderRoc,
                 {p["member"]: round(p["areaUnderRoc"], 4) if p["areaUnderRoc"]
                  else None for p in per_member})
    return rc
