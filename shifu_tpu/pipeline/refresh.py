"""`refresh` step — the continual-refresh controller as a pipeline step.

``shifu-tpu refresh`` runs ONE cycle attempt (trigger check → warm
retrain → AUC gate → promote → probation) and exits; ``--daemon`` keeps
the controller resident, polling the drift artifact / schedule forever —
the always-on variant that turns the one-shot pipeline into a service.

The step operates in REGISTRY mode: promotions/rollbacks commit the
``<modelset>/serving/serving.json`` journal (scorers build un-warmed —
no AOT compile cost in the controller process); a serving fleet
re-resolves the journal via ``ModelRegistry.restore`` on restart, and
probation reads the fleet's SERVE heartbeats for SLO burn.  An
in-process server attachment (bench / embedded use) goes through
:class:`shifu_tpu.refresh.RefreshController` directly instead.
"""

from __future__ import annotations

import logging
import os

from ..config.validator import ModelStep
from .processor import BasicProcessor

log = logging.getLogger(__name__)


class RefreshProcessor(BasicProcessor):
    step = ModelStep.REFRESH

    def process(self) -> int:
        from ..config.errors import ErrorCode, ShifuError
        from ..refresh import RefreshController, drift_columns_for
        from ..serve.registry import ModelRegistry

        models_dir = self.paths.models_dir
        if not any(f.startswith("model")
                   for f in (os.listdir(models_dir)
                             if os.path.isdir(models_dir) else [])):
            raise ShifuError(
                ErrorCode.ERROR_MODEL_FILE_NOT_FOUND,
                "`refresh` needs a trained incumbent — run `train` "
                "first")
        key = os.path.basename(os.path.abspath(self.dir))
        registry = ModelRegistry(
            state_dir=os.path.join(self.dir, "serving"))
        # registry mode: no AOT warm in the controller process — the
        # serving fleet re-resolves serving.json and warms its own
        registry.restore(key, models_dir, warm=False)
        ctrl = RefreshController(
            self.dir, registry=registry, key=key, warm=False,
            drift_columns=drift_columns_for(self.dir))
        poll = float(self.params.get("poll") or 2.0)
        ctrl.start()
        try:
            if self.params.get("daemon"):
                log.info("refresh daemon up: key=%s poll=%.1fs "
                         "(interrupt to stop)", key, poll)
                try:
                    ctrl.run(poll_s=poll)
                except KeyboardInterrupt:
                    log.info("refresh daemon stopped")
                return 0
            outcome = ctrl.run_once(poll_s=poll)
            log.info("refresh cycle outcome: %s (generation %d)",
                     outcome, registry.generation(key))
            return 0
        finally:
            ctrl.stop()
