"""`convert` step — reference ``shifu convert`` /
``util/IndependentTreeModelUtils`` (zip <-> binary model specs).

Our models are already self-contained npz blobs; convert maps npz <-> a
human-readable JSON spec (weights inlined) for diffing/porting.
"""

from __future__ import annotations

import glob
import json
import logging
import os

import numpy as np

from .. import ioutil

log = logging.getLogger(__name__)


def model_to_json(path: str, out_path: str) -> None:
    data = np.load(path)
    spec = json.loads(bytes(data["__spec__"]).decode())
    arrays = {k: data[k].tolist() for k in data.files if k != "__spec__"}
    ioutil.atomic_write_text(out_path,
                             json.dumps({"spec": spec,
                                         "arrays": arrays}))


def json_to_model(path: str, out_path: str) -> None:
    import io
    with open(path) as f:
        doc = json.load(f)
    arrays = {}
    for k, v in doc["arrays"].items():
        a = np.asarray(v)
        if k.startswith(("sf",)):
            a = a.astype(np.int32)
        elif k.startswith(("lm",)):
            a = a.astype(np.uint8)
        else:
            a = a.astype(np.float32)
        arrays[k] = a
    arrays["__spec__"] = np.frombuffer(
        json.dumps(doc["spec"]).encode(), np.uint8)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    ioutil.atomic_write_bytes(out_path, buf.getvalue())


def run_convert(model_set_dir: str, params: dict) -> int:
    models_dir = os.path.join(os.path.abspath(model_set_dir), "models")
    to_binary = params.get("tob")
    n = 0
    if to_binary:
        for p in sorted(glob.glob(os.path.join(models_dir, "model*.json"))):
            out = p[:-5]  # strip .json -> original ext embedded in stem
            json_to_model(p, out)
            log.info("convert %s -> %s", p, out)
            n += 1
    else:
        for p in sorted(glob.glob(os.path.join(models_dir, "model*.*"))):
            if p.endswith(".json"):
                continue
            out = p + ".json"
            model_to_json(p, out)
            log.info("convert %s -> %s", p, out)
            n += 1
    if n == 0:
        log.error("no models found in %s", models_dir)
        return 1
    return 0
