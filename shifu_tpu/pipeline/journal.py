"""Per-step commit journals — the pipeline's crash-consistency spine.

The reference pipeline got step atomicity from Hadoop (a failed MR job
leaves no ``_SUCCESS`` marker and re-runs whole); this rebuild writes
artifacts directly, so a crash mid-``norm``/``stats``/``train`` used to
leave a directory of committed-*looking* partials the next run happily
consumed.  The journal closes that hole:

- every step owns ``tmp/journal/<STEP>.json`` (atomic rename on every
  update, never torn itself);
- ``BasicProcessor.run()`` marks it ``running`` on entry and
  ``complete`` on success — a journal stuck at ``running`` IS the torn-
  step detector;
- steps with resumable sub-work (norm shards, stats chunks) record one
  **item** per committed unit with the exact byte sizes of its files;
  on re-run :meth:`arm` hands back only the items that (a) belong to an
  interrupted run with the SAME input signature and (b) still verify
  against the filesystem — a truncated committed-looking file simply
  drops out and its unit re-runs;
- downstream preconditions (train needs norm) check journal
  completeness + artifact verification, not mere file existence.

Journals are advisory for legacy model sets: a missing journal means
"pre-journal artifacts, trust the files" so existing sets keep working.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional

from ..ioutil import atomic_write_json

log = logging.getLogger(__name__)

JOURNAL_VERSION = 1

RUNNING = "running"
COMPLETE = "complete"


class StepJournal:
    def __init__(self, path: str, step: str, root: str):
        self.path = path
        self.step = step
        self.root = root               # file paths record relative to this
        self.doc: dict = self._load()
        # tear state of the PREVIOUS run, frozen before open_run() marks
        # this one running — the resume decision reads this, never the
        # live status (which this run owns)
        self.was_torn: bool = self.is_torn()

    # ------------------------------------------------------------- state
    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                doc = json.load(f)
            if doc.get("version") == JOURNAL_VERSION \
                    and doc.get("step") == self.step:
                return doc
        except (OSError, ValueError):
            pass
        return {"version": JOURNAL_VERSION, "step": self.step,
                "status": None, "signature": None, "items": {}}

    def _flush(self) -> None:
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        atomic_write_json(self.path, self.doc)

    @property
    def status(self) -> Optional[str]:
        return self.doc.get("status")

    @property
    def exists(self) -> bool:
        return self.doc.get("status") is not None

    def is_torn(self) -> bool:
        """A previous run started this step and never committed."""
        return self.exists and self.status != COMPLETE

    # --------------------------------------------------------- lifecycle
    def open_run(self) -> None:
        """Mark the step running.  Signature/items from a previous torn
        run are PRESERVED — :meth:`arm` decides whether they are a valid
        resume base or stale garbage."""
        self.was_torn = self.is_torn()
        self.doc["status"] = RUNNING
        self.doc["run_id"] = f"{os.getpid()}-{int(time.time() * 1000)}"
        self._flush()

    def complete(self, **meta) -> None:
        self.doc["status"] = COMPLETE
        if meta:
            self.doc.setdefault("meta", {}).update(meta)
        self._flush()

    # ------------------------------------------------------------- items
    def arm(self, signature: dict, resume: bool = True) -> Dict[str, dict]:
        """Bind this run to ``signature`` and return the verified resume
        items from an interrupted previous run (empty when the previous
        run completed, the signature changed, verification fails, or
        ``resume=False``).  Unverifiable items are dropped from the
        journal so the caller's view and the journal agree."""
        prev_sig = self.doc.get("signature")
        prev_items = dict(self.doc.get("items") or {})
        # only a TORN previous run resumes; a completed one re-runs whole
        # (idempotent rewrite keeps mtime-based staleness checks honest)
        resumable = (resume and prev_sig == signature
                     and self.was_torn and prev_items)
        kept: Dict[str, dict] = {}
        if resumable:
            for name, meta in prev_items.items():
                if self.verify_item(meta):
                    kept[name] = meta
                else:
                    log.warning("journal %s: item %r fails verification "
                                "(torn artifact) — its unit will re-run",
                                self.step, name)
        self.doc["signature"] = signature
        self.doc["items"] = kept
        self._flush()
        return kept

    def commit_item(self, name: str, files: Optional[List[str]] = None,
                    **meta) -> None:
        """Record one committed unit of work.  ``files`` are pinned with
        their exact sizes — the torn-artifact check on resume."""
        if files:
            meta["files"] = [[os.path.relpath(p, self.root),
                              os.path.getsize(p)] for p in files]
        self.doc["items"][name] = meta
        self._flush()

    def item(self, name: str) -> Optional[dict]:
        return (self.doc.get("items") or {}).get(name)

    def verify_item(self, meta: dict) -> bool:
        for rel, size in meta.get("files") or []:
            p = os.path.join(self.root, rel)
            try:
                if os.path.getsize(p) != int(size):
                    return False
            except OSError:
                return False
        return True

    def verify_all(self) -> bool:
        """Every recorded item's files still match their committed sizes
        (the downstream-precondition completeness check)."""
        return all(self.verify_item(m)
                   for m in (self.doc.get("items") or {}).values())
