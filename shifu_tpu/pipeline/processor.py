"""Pipeline processors — step orchestration.

Analogue of the reference's processor layer (``core/processor/``): one
processor per CLI step with shared setup/teardown (config load, validation,
ColumnConfig save) in ``BasicProcessor`` (reference
``BasicModelProcessor.java``).
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import List, Optional

from ..config import (ColumnConfig, ModelConfig, PathFinder,
                      load_column_configs, save_column_configs)
from ..config.validator import ModelStep, probe

log = logging.getLogger(__name__)


class BasicProcessor:
    """Shared step setup/teardown (reference ``BasicModelProcessor.java``)."""

    step: ModelStep = ModelStep.NEW

    def __init__(self, model_set_dir: str = ".", params: Optional[dict] = None):
        self.dir = os.path.abspath(model_set_dir)
        self.params = params or {}
        self.model_config: Optional[ModelConfig] = None
        self.column_configs: List[ColumnConfig] = []
        self.paths: Optional[PathFinder] = None

    # ------------------------------------------------------------ lifecycle
    def setup(self, require_columns: bool = True) -> None:
        mc_path = os.path.join(self.dir, "ModelConfig.json")
        if not os.path.isfile(mc_path):
            raise FileNotFoundError(
                f"{mc_path} not found — run `shifu-tpu new <name>` first")
        self.model_config = ModelConfig.load(mc_path)
        self.paths = PathFinder(self.model_config, self.dir)
        probe(self.model_config, self.step, self.dir)
        cc_path = self.paths.column_config_path
        if os.path.isfile(cc_path):
            self.column_configs = load_column_configs(cc_path)
        elif require_columns:
            raise FileNotFoundError(
                f"{cc_path} not found — run `shifu-tpu init` first")
        self.paths.ensure_dirs()

    def _abs(self, p: Optional[str]) -> Optional[str]:
        """Resolve a config-relative path against the model-set dir.
        Scheme'd URIs (hdfs://, s3://, ...) pass through untouched so the
        data layer can reject them with the proper error code."""
        if p is None:
            return None
        if "://" in p:
            return p
        return p if os.path.isabs(p) else os.path.normpath(
            os.path.join(self.dir, p))

    def save_column_configs(self) -> None:
        save_column_configs(self.column_configs, self.paths.column_config_path)

    def save_model_config(self) -> None:
        self.model_config.save(self.paths.model_config_path)

    def run(self) -> int:
        t0 = time.time()
        log.info("step %s start", self.step.name)
        self.setup()
        code = self.process()
        log.info("step %s done in %.2fs", self.step.name, time.time() - t0)
        return code

    def process(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    # -------------------------------------------------------------- helpers
    def backup(self, path: str) -> None:
        """Keep one backup generation of a config file before overwrite."""
        if os.path.isfile(path):
            bdir = self.paths.backup_dir
            os.makedirs(bdir, exist_ok=True)
            shutil.copy2(path, os.path.join(bdir, os.path.basename(path)))
