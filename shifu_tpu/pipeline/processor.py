"""Pipeline processors — step orchestration.

Analogue of the reference's processor layer (``core/processor/``): one
processor per CLI step with shared setup/teardown (config load, validation,
ColumnConfig save) in ``BasicProcessor`` (reference
``BasicModelProcessor.java``).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import List, Optional

from .. import faults, ioutil, obs
from ..config import (ColumnConfig, ModelConfig, PathFinder,
                      load_column_configs, save_column_configs)
from ..config.validator import ModelStep, probe
from .journal import StepJournal

log = logging.getLogger(__name__)


class BasicProcessor:
    """Shared step setup/teardown (reference ``BasicModelProcessor.java``)."""

    step: ModelStep = ModelStep.NEW
    require_columns: bool = True       # INIT creates ColumnConfig itself

    @property
    def profile_name(self) -> str:
        """profile.json key — override when several processors share a
        ModelStep (encode runs under EVAL validation rules)."""
        return self.step.name

    def __init__(self, model_set_dir: str = ".", params: Optional[dict] = None):
        self.dir = os.path.abspath(model_set_dir)
        self.params = params or {}
        self.model_config: Optional[ModelConfig] = None
        self.column_configs: List[ColumnConfig] = []
        self.paths: Optional[PathFinder] = None
        self.journal: Optional[StepJournal] = None

    # ------------------------------------------------------------ lifecycle
    def setup(self, require_columns: Optional[bool] = None) -> None:
        if require_columns is None:
            require_columns = self.require_columns
        mc_path = os.path.join(self.dir, "ModelConfig.json")
        if not os.path.isfile(mc_path):
            raise FileNotFoundError(
                f"{mc_path} not found — run `shifu-tpu new <name>` first")
        self.model_config = ModelConfig.load(mc_path)
        self.paths = PathFinder(self.model_config, self.dir)
        probe(self.model_config, self.step, self.dir)
        cc_path = self.paths.column_config_path
        if os.path.isfile(cc_path):
            self.column_configs = load_column_configs(cc_path)
        elif require_columns:
            raise FileNotFoundError(
                f"{cc_path} not found — run `shifu-tpu init` first")
        self.paths.ensure_dirs()
        self.journal = StepJournal(
            self.paths.journal_path(self.profile_name), self.profile_name,
            self.dir)
        self._check_step_preconditions()

    def _check_step_preconditions(self) -> None:
        """Ordered-pipeline guard: running a step before its inputs exist
        fails with a coded hint instead of a raw traceback deep in the
        step (stats -> norm -> train dependency chain)."""
        from ..config.errors import ErrorCode, ShifuError
        s = self.step
        if s in (ModelStep.NORMALIZE, ModelStep.VARSELECT, ModelStep.TRAIN):
            cand = [c for c in self.column_configs or [] if c.is_candidate()]
            if cand and not any((c.num_bins() or 0) > 0
                                or c.columnStats.mean is not None
                                for c in cand):
                raise ShifuError(
                    ErrorCode.ERROR_STEP_PRECONDITION,
                    f"`{s.value.lower()}` needs column statistics — run "
                    "`stats` first")
        if s == ModelStep.TRAIN:
            if not (os.path.isfile(os.path.join(self.paths.norm_dir,
                                                "schema.json"))
                    or os.path.isfile(os.path.join(self.paths.clean_dir,
                                                   "schema.json"))):
                raise ShifuError(
                    ErrorCode.ERROR_STEP_PRECONDITION,
                    "`train` needs the materialized data plane — run "
                    "`norm` first")
            # journal completeness, not just file existence: a norm run
            # that died mid-step (or whose committed shards were later
            # truncated) must not feed the trainers half a dataset.
            # Absence of a journal = pre-journal artifacts, trust files.
            nj = StepJournal(self.paths.journal_path("NORMALIZE"),
                             "NORMALIZE", self.dir)
            if nj.is_torn():
                raise ShifuError(
                    ErrorCode.ERROR_TORN_ARTIFACT,
                    "the last `norm` run did not complete (journal "
                    "status=running) — re-run `norm` (it resumes at the "
                    "first uncommitted shard)")
            if nj.status and not nj.verify_all():
                raise ShifuError(
                    ErrorCode.ERROR_TORN_ARTIFACT,
                    "materialized norm shards no longer match their "
                    "journaled sizes (torn/corrupted artifact) — re-run "
                    "`norm`")

    def _abs(self, p: Optional[str]) -> Optional[str]:
        """Resolve a config-relative path against the model-set dir.
        Scheme'd URIs (hdfs://, s3://, ...) pass through untouched so the
        data layer can reject them with the proper error code."""
        if p is None:
            return None
        if "://" in p:
            return p
        return p if os.path.isabs(p) else os.path.normpath(
            os.path.join(self.dir, p))

    def save_column_configs(self) -> None:
        save_column_configs(self.column_configs, self.paths.column_config_path)

    def save_model_config(self) -> None:
        self.model_config.save(self.paths.model_config_path)

    def run(self) -> int:
        t0 = time.time()
        log.info("step %s start", self.step.name)
        telemetry = obs.enabled()
        if telemetry:
            obs.ensure_compile_listener()
        heartbeat = exporter = None
        code: Optional[int] = None
        try:
            with obs.span(self.profile_name, kind="step") as root:
                with obs.span("setup", kind="phase"):
                    self.setup()
                # live observability plane: per-process heartbeats under
                # <modelset>/telemetry/health/ (the `monitor` CLI tails
                # them) + periodic OpenMetrics/JSON registry snapshots —
                # both factories return None when telemetry is off, so
                # the disabled path starts no thread and touches no file
                heartbeat = obs.start_heartbeat(self.paths.health_dir,
                                                step=self.profile_name)
                exporter = obs.start_exporter(self.paths.telemetry_dir,
                                              step=self.profile_name)
                # torn-run detection: the journal stays "running" until
                # the step commits, so a crash anywhere below leaves the
                # marker the next run (and downstream preconditions) read
                self.journal.open_run()
                with self._device_trace(), \
                        obs.span("process", kind="phase"):
                    code = self.process()
                root.set(exit_code=code)
                if code == 0:
                    self.journal.complete(exit_code=0)
        finally:
            # retire the live plane, then flush — even when the step
            # raised: a crashed run's partial trace (with the error-
            # marked span) is exactly the one you want to read, and the
            # final heartbeat (state=exited) is how the monitor tells a
            # clean exit from a silent death
            if heartbeat is not None:
                heartbeat.stop(exit_code=code)
            if exporter is not None:
                exporter.stop()
            if telemetry:
                self._flush_telemetry()
        total = time.time() - t0
        log.info("step %s done in %.2fs", self.step.name, total)
        self._write_profile(total)
        return code

    def _device_trace(self):
        """``shifu-tpu <step> --profile [dir]`` / ``-Dshifu.profile=<dir>``:
        wrap the step in a ``jax.profiler`` trace (XLA device timeline,
        viewable in TensorBoard/Perfetto) — see ``obs/profiler.py``.  The
        wall-clock ``phase()`` spans stay always-on (when telemetry is);
        this knob adds the compiled-op view when asked."""
        from ..obs.profiler import profile_step
        return profile_step(self.step.name.lower())

    def _flush_telemetry(self) -> None:
        """Append this run's spans/events + metrics snapshot to
        ``<modelset>/telemetry/trace.jsonl`` — the file ``analysis
        --telemetry`` renders.  Device-memory high-water samples here, at
        the step boundary (the per-step peak is the YARN-container-memory
        counter analogue)."""
        try:
            obs.sample_device_memory()
            # step-level surface of the shape-churn sentinel: recompiles
            # accumulated during THIS step (the registry resets at flush)
            # get one loud summary line beside the per-name warn-once
            rec = next((m.get("value") for m in obs.snapshot()
                        if m.get("name") == "xla.recompiles"), None)
            if rec:
                log.warning(
                    "step %s rebuilt %d executable(s) for new input "
                    "signatures (shape churn defeats the compile cache "
                    "— see `analysis --telemetry --utilization`)",
                    self.profile_name, int(rec))
            path = self.paths.telemetry_trace_path if self.paths else \
                os.path.join(self.dir, "telemetry", "trace.jsonl")
            obs.flush(path, step=self.profile_name)
        except Exception:                   # telemetry must never fail a step
            log.debug("telemetry flush failed", exc_info=True)

    # ------------------------------------------------------------ profiling
    def phase(self, name: str):
        """Time a named phase inside the step (reference aux tracing role,
        SURVEY §5): accumulates into ``tmp/profile.json`` per step AND
        opens a telemetry span nested under the step's root (no-op when
        telemetry is off)."""
        return _PhaseSpan(self._phases, name)

    @property
    def _phases(self) -> dict:
        if not hasattr(self, "_phase_spans"):
            self._phase_spans = {}
        return self._phase_spans

    def _write_profile(self, total_s: float) -> None:
        try:
            path = os.path.join(self.paths.tmp_dir, "profile.json")
            doc = {}
            if os.path.isfile(path):
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (json.JSONDecodeError, OSError):
                    doc = {}            # self-heal a truncated file
            doc[self.profile_name] = {
                "total_s": round(total_s, 3),
                "phases_s": {k: round(v, 3)
                             for k, v in self._phases.items()}}
            os.makedirs(self.paths.tmp_dir, exist_ok=True)
            ioutil.atomic_write_json(path, doc)
        except Exception:                       # profiling must never fail
            log.debug("profile write failed", exc_info=True)

    def process(self) -> int:  # pragma: no cover - abstract
        raise NotImplementedError

    # -------------------------------------------------------------- helpers
    def backup(self, path: str) -> None:
        """Keep one backup generation of a config file before overwrite."""
        if os.path.isfile(path):
            bdir = self.paths.backup_dir
            os.makedirs(bdir, exist_ok=True)
            shutil.copy2(path, os.path.join(bdir, os.path.basename(path)))


class _PhaseSpan:
    def __init__(self, store: dict, name: str):
        self.store = store
        self.name = name
        self._obs = None
        self._pending: dict = {}

    def __enter__(self):
        faults.fire("step", "phase", self.name)
        self._obs = obs.span(self.name, kind="phase", **self._pending)
        self._obs.__enter__()
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.store[self.name] = self.store.get(self.name, 0.0) \
            + (time.perf_counter() - self.t0)
        self._obs.__exit__(*exc)
        return False

    def set(self, **attrs):
        """Attach telemetry attributes (e.g. ``rows=`` for rows/sec in
        the report); usable before or inside the ``with``; no-op when
        telemetry is off."""
        if self._obs is None:
            self._pending.update(attrs)
        else:
            self._obs.set(**attrs)
        return self
