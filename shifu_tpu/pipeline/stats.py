"""`stats` step: per-column statistics + binning (+ PSI, correlation).

Replaces the reference's Pig/MR stats chain (SURVEY.md §3.2:
``StatsSpdtI.pig`` -> ``UpdateBinningInfo`` MR -> ColumnConfig update,
``MapReducerStatsWorker.java:104-176``) with two streamed device passes; see
``ops/binning.py``.  Fills every ``ColumnStats``/``ColumnBinning`` field the
reference writes: mean/std/min/max/median/p25/p75, missing counts, KS/IV/WOE
(count + weighted), per-bin counts/pos-rates/woe, skewness/kurtosis, PSI.
"""

from __future__ import annotations

import hashlib
import json
import logging
import math
import os
import time
from typing import Dict, List, Optional

import numpy as np

from .. import faults, obs
from ..config import ColumnConfig
from ..ioutil import atomic_savez, atomic_write_text
from ..config.validator import ModelStep
from ..data import DataSource
from ..data.extract import ChunkExtractor
from ..data.parsepool import iter_extracted
from ..ops.binning import (CategoricalAccumulator, ColumnBinner,
                           NumericAccumulator)
from ..ops.correlation import CorrelationAccumulator
from ..ops.stats_math import column_metrics, pos_rate, psi
from .processor import BasicProcessor

log = logging.getLogger(__name__)


class StatsProcessor(BasicProcessor):
    step = ModelStep.STATS

    def process(self) -> int:
        mc = self.model_config
        extractor = ChunkExtractor(mc, self.column_configs)
        num_cols = extractor.numeric_cols
        cat_cols = extractor.categorical_cols
        source = DataSource(self._abs(mc.dataSet.dataPath), mc.dataSet.dataDelimiter,
                            header_path=self._abs(mc.dataSet.headerPath),
                            header_delimiter=mc.dataSet.headerDelimiter)

        from ..config import environment
        from ..config.model_config import BinningAlgorithm
        from ..parallel.mesh import device_mesh
        exact_alg = mc.stats.binningAlgorithm in (BinningAlgorithm.MunroPat,
                                                  BinningAlgorithm.MunroPatI)
        # pure data-parallel mesh: chunk rows shard across every chip and
        # the per-chunk reductions psum on ICI — the reference's stats MR
        # fan-out (``MapReducerStatsWorker.java:111-139``); degenerates to
        # the single-chip layout on a 1-device rig
        mesh = device_mesh()
        num_acc = NumericAccumulator(
            n_cols=len(num_cols), exact=exact_alg,
            unit_weight=not extractor.weight_name, mesh=mesh,
            fused_budget=environment.get_int(
                "shifu.stats.fusedBudgetBytes", 1 << 30))
        cat_acc = CategoricalAccumulator()
        psi_col = mc.stats.psiColumnName if self.params.get("psi") or \
            mc.stats.psiColumnName else None
        rate = float(mc.stats.sampleRate)
        # ONE-PASS fused sweep (default): moments + fine histogram +
        # categorical aggregation in a single streamed read — each chunk
        # is read, parsed and shipped H2D once (device-resident up to the
        # fused budget; the overflow tail takes sketch-first provisional
        # boundaries with device-side refinement, ops/sketches.py).
        # MunroPat exact binning keeps the two-pass flow (it materializes
        # rows anyway); ``-Dshifu.stats.onePass=false`` restores it.
        fused = not exact_alg and environment.get_bool(
            "shifu.stats.onePass", True)
        want_corr = bool(self.params.get("correlation"))
        corr_acc = None

        # mid-sweep checkpointing (fused path only): every N chunks the
        # accumulators snapshot to tmp/stats/partial_sweep.npz; a crash
        # resumes at the first un-checkpointed chunk.  0 = off (default;
        # checkpointing routes the sweep through the provisional-grid
        # path, trading the resident-exact fast path for resumability).
        ckpt_chunks = environment.get_int("shifu.stats.checkpointChunks", 0)
        partial_path = self.paths.stats_partial_path
        sig = self._sweep_signature(source, fused, ckpt_chunks)
        items = self.journal.arm(sig, resume=bool(ckpt_chunks and fused))
        resume_chunk, total_rows = 0, 0
        if ckpt_chunks and fused and items.get("sweep"):
            restored = _load_partial(partial_path, _sig_hash(sig))
            if restored is not None:
                meta, arrays = restored
                resume_chunk = int(meta["chunk_next"])
                total_rows = int(meta["total_rows"])
                if num_cols:
                    num_acc.restore_checkpoint(
                        {k[4:]: v for k, v in arrays.items()
                         if k.startswith("num_")})
                cat_acc.load_state(meta["cat"], arrays)
                obs.counter("stats.resumed_chunks").inc(resume_chunk)
                log.info("stats: resuming fused sweep at chunk %d "
                         "(%d rows already accumulated)", resume_chunk,
                         total_rows)
        elif os.path.isfile(partial_path):
            try:                       # stale partial from another config
                os.remove(partial_path)
            except OSError:
                pass

        def save_partial(chunk_next: int, rows: int) -> None:
            arrays: Dict[str, np.ndarray] = {}
            if num_cols:
                for k, v in num_acc.checkpoint_state().items():
                    arrays["num_" + k] = v
            cat_meta, cat_arrays = cat_acc.state_lists()
            arrays.update(cat_arrays)
            meta = {"version": 1, "chunk_next": chunk_next,
                    "total_rows": rows, "sig": _sig_hash(sig),
                    "cat": cat_meta}
            arrays["__meta__"] = np.frombuffer(
                json.dumps(meta).encode(), np.uint8)
            atomic_savez(partial_path, **arrays)
            self.journal.commit_item("sweep", files=[partial_path],
                                     chunk_next=chunk_next)

        def cat_update(ex, tgt) -> None:
            missing_set = {m.strip().lower()
                           for m in extractor.missing_values}
            for cc in cat_cols:
                vals = ex.categorical[cc.columnName]
                import pandas as pd
                s = pd.Series(vals, dtype=str).str.strip()
                valid = (~s.str.lower().isin(missing_set)).to_numpy()
                cat_acc.update(cc.columnName, s.to_numpy(), valid, tgt,
                               ex.weight, stripped=True)

        def binarized(ex):
            # multi-class: bin pos/neg stats binarize as class 0 vs rest
            # so KS/IV/WOE stay defined (class ids are ordinal only)
            return (ex.target > 0).astype(ex.target.dtype) \
                if extractor.multiclass else ex.target

        sweep_t0 = time.perf_counter()
        if fused:
            with self.phase("fused_sweep") as ph:
                for ci, ex in iter_extracted(
                        source, extractor, rate=rate,
                        cache_root=self.paths.raw_cache_dir,
                        start_chunk=resume_chunk):
                    faults.fire("stats", "chunk", ci)
                    if ex.n == 0:
                        continue
                    total_rows += ex.n
                    tgt = binarized(ex)
                    if num_cols:
                        num_acc.update_fused(ex.numeric, ex.numeric_valid,
                                             tgt, ex.weight)
                        # a resumed sweep skipped chunks the piggyback
                        # correlation never saw — it falls back to the
                        # dedicated full-pass below (corr_acc stays None)
                        if want_corr and not cat_cols and not resume_chunk:
                            if corr_acc is None:
                                # Pearson is shift-invariant; the first
                                # chunk's means condition the f32 sums
                                with np.errstate(invalid="ignore"):
                                    off = np.nanmean(np.where(
                                        ex.numeric_valid, ex.numeric,
                                        np.nan), axis=0)
                                corr_acc = CorrelationAccumulator(
                                    n_cols=len(num_cols),
                                    offset=np.nan_to_num(off), mesh=mesh)
                            corr_acc.update(np.nan_to_num(ex.numeric),
                                            ex.numeric_valid)
                    cat_update(ex, tgt)
                    if ckpt_chunks and (ci + 1) % ckpt_chunks == 0:
                        save_partial(ci + 1, total_rows)
                ph.set(rows=total_rows)
            if total_rows == 0:
                raise RuntimeError("stats: dataset is empty after "
                                   "filtering")
            if num_cols:
                num_acc.finalize_fused()
        else:
            # ---------------- pass 1: moments/min/max (numeric)
            with self.phase("pass1_moments") as ph:
                for ci, ex in iter_extracted(
                        source, extractor, rate=rate,
                        cache_root=self.paths.raw_cache_dir):
                    faults.fire("stats", "chunk", ci)
                    if ex.n == 0:
                        continue
                    total_rows += ex.n
                    if num_cols:
                        num_acc.update_moments(ex.numeric, ex.numeric_valid)
                ph.set(rows=total_rows)
            if total_rows == 0:
                raise RuntimeError("stats: dataset is empty after "
                                   "filtering")
            if num_cols:
                num_acc.finalize_range()

            # ---------------- pass 2: fine histograms + categorical
            # correlation piggybacks pass 2 when only numerics
            # participate; categorical pos-rate encodings need finished
            # bin stats (3rd pass)
            if want_corr and num_cols and not cat_cols:
                corr_acc = CorrelationAccumulator(
                    n_cols=len(num_cols), offset=num_acc.moments["mean"],
                    mesh=mesh)
            with self.phase("pass2_histograms").set(rows=total_rows):
                for ci, ex in iter_extracted(
                        source, extractor, rate=rate,
                        cache_root=self.paths.raw_cache_dir):
                    if ex.n == 0:
                        continue
                    tgt = binarized(ex)
                    if num_cols:
                        num_acc.update_histogram(ex.numeric,
                                                 ex.numeric_valid,
                                                 tgt, ex.weight)
                        if corr_acc is not None:
                            corr_acc.update(np.nan_to_num(ex.numeric),
                                            ex.numeric_valid)
                    cat_update(ex, tgt)
        # ---------------- finalize numeric columns
        with self.phase("finalize"):
            if num_cols:
                self._finalize_numeric(num_cols, num_acc, total_rows)
            self._finalize_categorical(cat_cols, cat_acc, total_rows)

        if want_corr:
            with self.phase("correlation"):
                if corr_acc is not None:  # numeric-only: done in pass 2
                    self._write_corr_matrix(
                        corr_acc.finalize(),
                        [c.columnName for c in num_cols], 0)
                else:
                    self._compute_correlation(source, extractor, rate)
        if psi_col:
            with self.phase("psi"):
                self._compute_psi(source, extractor, psi_col)
        if self.params.get("rebin"):
            self._dynamic_rebin()

        obs.counter("stats.rows").inc(total_rows)
        obs.gauge("stats.columns").set(len(num_cols) + len(cat_cols))
        obs.gauge("stats.rows_per_sec").set(
            total_rows / max(time.perf_counter() - sweep_t0, 1e-9))
        self.save_column_configs()
        if os.path.isfile(partial_path):
            try:                       # the sweep committed — drop partials
                os.remove(partial_path)
            except OSError:
                pass
        log.info("stats: %d rows, %d numeric, %d categorical columns",
                 total_rows, len(num_cols), len(cat_cols))
        return 0

    def _sweep_signature(self, source: DataSource, fused: bool,
                         ckpt_chunks: int) -> dict:
        """Inputs + config identity a resumed sweep must match."""
        mc = self.model_config
        files = []
        for f in source.files:
            try:
                st = os.stat(f)
                files.append([os.path.basename(f), st.st_size,
                              st.st_mtime_ns])
            except OSError:
                files.append([f, None, None])
        return {"files": files,
                "sampleRate": float(mc.stats.sampleRate),
                "binningAlgorithm": mc.stats.binningAlgorithm.value,
                "binningMethod": mc.stats.binningMethod.value,
                "maxNumBin": int(mc.stats.maxNumBin),
                "fused": bool(fused),
                "checkpointChunks": int(ckpt_chunks)}


    # ------------------------------------------------------------- numeric
    def _finalize_numeric(self, num_cols: List[ColumnConfig],
                          acc: NumericAccumulator, total_rows: int) -> None:
        mc = self.model_config
        # MunroPat/MunroPatI: exact data quantiles; everything else: the
        # streaming fine-histogram sketch (SPDT-family stand-in), reduced
        # to boundaries/bin-stats/percentiles ON DEVICE — the fine
        # histogram never crosses the host link (finalize_sketch)
        sketch = None
        if acc.exact:
            boundaries = acc.compute_boundaries_exact(mc.stats.binningMethod,
                                                      mc.stats.maxNumBin)
        else:
            sketch = acc.finalize_sketch(mc.stats.binningMethod,
                                         mc.stats.maxNumBin)
            boundaries = sketch[0]
        # skew/kurt directly from central moments (more stable than power sums)
        cnt = np.maximum(acc.moments["count"], 1.0)
        m2 = acc.moments["M2"] / cnt
        m3 = acc.moments["M3"] / cnt
        m4 = acc.moments["M4"] / cnt
        with np.errstate(invalid="ignore", divide="ignore"):
            skew = np.where(m2 > 0, m3 / np.power(np.maximum(m2, 1e-300), 1.5), 0.0)
            kurt = np.where(m2 > 0, m4 / np.maximum(m2 ** 2, 1e-300) - 3.0, 0.0)
            std = np.sqrt(acc.moments["M2"] / np.maximum(cnt - 1, 1.0))

        for i, cc in enumerate(num_cols):
            bnds = boundaries[i]
            # exact mode counts from the materialized rows (mid-bucket
            # boundaries would misassign ties through the sketch)
            agg = acc.bin_counts_exact(i, bnds) if acc.exact \
                else sketch[1][i]              # [bins+1, 4]
            cpos, cneg, wpos, wneg = agg[:, 0], agg[:, 1], agg[:, 2], agg[:, 3]
            cm = column_metrics(cneg[None, :], cpos[None, :])
            wm = column_metrics(wneg[None, :], wpos[None, :])
            st, bn = cc.columnStats, cc.columnBinning
            count = float(acc.moments["count"][i])
            st.totalCount = total_rows
            st.validNumCount = int(count)
            st.missingCount = int(acc.missing[i])
            st.missingPercentage = float(acc.missing[i] / max(total_rows, 1))
            st.min = _f(acc.moments["min"][i] if count else None)
            st.max = _f(acc.moments["max"][i] if count else None)
            st.mean = _f(acc.moments["mean"][i] if count else None)
            st.stdDev = _f(std[i] if count > 1 else None)
            st.skewness = _f(skew[i])
            st.kurtosis = _f(kurt[i])
            p = acc.percentile(i, [0.25, 0.5, 0.75]) if acc.exact \
                else sketch[2][i]
            st.p25th, st.median, st.p75th = _f(p[0]), _f(p[1]), _f(p[2])
            st.distinctCount = acc.distinct_estimate(i) if acc.exact \
                else int(sketch[3][i])
            st.ks = _f(cm.ks[0])
            st.iv = _f(cm.iv[0])
            st.woe = _f(cm.woe[0])
            st.weightedKs = _f(wm.ks[0])
            st.weightedIv = _f(wm.iv[0])
            st.weightedWoe = _f(wm.woe[0])
            bn.length = len(bnds) + 1
            bn.binBoundary = [float(b) for b in bnds]
            bn.binCategory = None
            bn.extra["binningAlgorithm"] = mc.stats.binningAlgorithm.value
            bn.binCountNeg = [int(x) for x in cneg]
            bn.binCountPos = [int(x) for x in cpos]
            bn.binWeightedNeg = [float(x) for x in wneg]
            bn.binWeightedPos = [float(x) for x in wpos]
            bn.binPosRate = _fl(pos_rate(cpos, cneg))
            bn.binCountWoe = _fl(cm.bin_woe[0])
            bn.binWeightedWoe = _fl(wm.bin_woe[0])

    # --------------------------------------------------------- categorical
    def _finalize_categorical(self, cat_cols: List[ColumnConfig],
                              acc: CategoricalAccumulator, total_rows: int) -> None:
        mc = self.model_config
        # reference hard cap regardless of cateMaxNumBin=0 ("uncapped"):
        # Constants.MAX_CATEGORICAL_BINC_COUNT = 10000
        max_cates = min(mc.stats.cateMaxNumBin or 10000, 10000)
        for cc in cat_cols:
            cats, counts, n_distinct, n_missing = acc.finalize(
                cc.columnName, max_cates)
            cpos, cneg, wpos, wneg = (counts[:, 0], counts[:, 1],
                                      counts[:, 2], counts[:, 3])
            cm = column_metrics(cneg[None, :], cpos[None, :])
            wm = column_metrics(wneg[None, :], wpos[None, :])
            st, bn = cc.columnStats, cc.columnBinning
            st.totalCount = total_rows
            st.validNumCount = total_rows - n_missing
            st.missingCount = n_missing
            st.missingPercentage = n_missing / max(total_rows, 1)
            st.distinctCount = n_distinct
            pr = pos_rate(cpos, cneg)
            st.ks = _f(cm.ks[0])
            st.iv = _f(cm.iv[0])
            st.woe = _f(cm.woe[0])
            st.weightedKs = _f(wm.ks[0])
            st.weightedIv = _f(wm.iv[0])
            st.weightedWoe = _f(wm.woe[0])
            # categorical "mean/std": pos-rate weighted stats, as the reference
            # reuses posRate as the numeric encoding of a category
            tot = cpos + cneg
            if tot.sum() > 0:
                mean = float(np.nansum(pr * tot) / tot.sum())
                st.mean = mean
                st.stdDev = float(np.sqrt(
                    np.nansum((np.nan_to_num(pr) - mean) ** 2 * tot) / max(tot.sum() - 1, 1)))
            bn.length = len(cats) + 1
            bn.binCategory = list(cats)
            bn.binBoundary = None
            bn.binCountNeg = [int(x) for x in cneg]
            bn.binCountPos = [int(x) for x in cpos]
            bn.binWeightedNeg = [float(x) for x in wneg]
            bn.binWeightedPos = [float(x) for x in wpos]
            bn.binPosRate = _fl(pr)
            bn.binCountWoe = _fl(cm.bin_woe[0])
            bn.binWeightedWoe = _fl(wm.bin_woe[0])

    # -------------------------------------------------------------- extras
    def _compute_correlation(self, source: DataSource,
                             extractor: ChunkExtractor,
                             rate: float) -> None:
        """Pairwise-complete Pearson over ALL candidates: numerics use raw
        values, categoricals their bin pos-rate encoding (reference
        ``CorrelationMapper.java:309-318``); each pair's sums count only
        rows valid in BOTH columns (``CorrelationWritable`` adjustCount)."""
        import pandas as pd
        num_cols = extractor.numeric_cols
        cat_cols = extractor.categorical_cols
        cols = num_cols + cat_cols
        # categorical value -> pos-rate lookup from the finished bin stats
        rate_maps = {}
        for cc in cat_cols:
            cats = cc.bin_category or []
            pr = cc.columnBinning.binPosRate or []
            rate_maps[cc.columnName] = {str(c): float(pr[i])
                                        for i, c in enumerate(cats)
                                        if i < len(pr) and pr[i] is not None}
        # offsets: pass-1 means for numerics, 0.5 for pos-rate encodings
        from ..parallel.mesh import device_mesh
        num_means = [c.columnStats.mean or 0.0 for c in num_cols]
        acc = CorrelationAccumulator(
            n_cols=len(cols),
            offset=np.asarray(num_means + [0.5] * len(cat_cols)),
            mesh=device_mesh())
        miss = {m.strip().lower() for m in extractor.missing_values}
        for ci, ex in iter_extracted(source, extractor, rate=rate,
                                     cache_root=self.paths.raw_cache_dir):
            if ex.n == 0:
                continue
            x = np.zeros((ex.n, len(cols)))
            v = np.zeros((ex.n, len(cols)), bool)
            if num_cols:
                x[:, :len(num_cols)] = np.nan_to_num(ex.numeric)
                v[:, :len(num_cols)] = ex.numeric_valid
            for j, cc in enumerate(cat_cols):
                s = pd.Series(ex.categorical[cc.columnName],
                              dtype=str).str.strip()
                enc = s.map(rate_maps[cc.columnName])
                ok = enc.notna().to_numpy() & \
                    ~s.str.lower().isin(miss).to_numpy()
                x[:, len(num_cols) + j] = enc.fillna(0.0).to_numpy()
                v[:, len(num_cols) + j] = ok
            acc.update(x, v)
        self._write_corr_matrix(acc.finalize(),
                                [c.columnName for c in cols], len(cat_cols))

    def _write_corr_matrix(self, corr: np.ndarray, names: List[str],
                           n_cat: int) -> None:
        path = self.paths.correlation_path
        lines = ["," + ",".join(names)]
        for i, n in enumerate(names):
            lines.append(n + "," + ",".join(
                f"{corr[i, j]:.6f}" for j in range(len(names))))
        atomic_write_text(path, "\n".join(lines) + "\n")
        log.info("correlation matrix (%d columns incl. %d categorical) -> %s",
                 len(names), n_cat, path)

    def _compute_psi(self, source: DataSource, extractor: ChunkExtractor,
                     psi_col: str) -> None:
        """PSI across units of ``psiColumnName`` (e.g. a time bucket):
        per-unit bin distributions vs the overall distribution."""
        binners = {}
        for cc in self.column_configs:
            if not cc.is_candidate() or cc.num_bins() == 0:
                continue
            if cc.is_categorical():
                binners[cc.columnName] = (cc, ColumnBinner(categories=cc.bin_category))
            else:
                binners[cc.columnName] = (cc, ColumnBinner(
                    boundaries=np.asarray(cc.bin_boundary)))
        # ONE flat count over (unit, column, bin) per chunk — columns pack
        # into a global offset bin space so wall-clock is flat in column
        # count (the round-2 per-(unit, column) bincount loop was O(U*C)
        # passes; reference runs $column_parallel Pig mappers, PSI.pig)
        col_list = list(binners.items())
        nb_list = [binner.num_bins + 1 for _, (_, binner) in col_list]
        offsets = np.concatenate([[0], np.cumsum(nb_list)]).astype(np.int64)
        total_bins = int(offsets[-1])
        unit_ids: Dict[str, int] = {}
        acc = np.zeros((0, total_bins), np.float64)   # [units, packed bins]
        rate = float(self.model_config.stats.sampleRate)
        if psi_col not in source.header:
            log.warning("psi column %s not found; skipping PSI", psi_col)
            return
        # keep_raw: the unit column rides the raw string plane, so this
        # pass parses through the pool but never serves from/writes the
        # raw cache (raw strings are not cached)
        for ci, ex in iter_extracted(source, extractor, rate=rate,
                                     keep_raw=True):
            if ex.n == 0:
                continue
            units = ex.raw.data[psi_col].to_numpy()  # raw values: numeric
            # unit columns keep numeric sort order in unitStats
            num_index = {c.columnName: i for i, c in enumerate(ex.numeric_cols)}
            idx_mat = np.empty((ex.n, len(col_list)), np.int64)
            for col_i, (name, (cc, binner)) in enumerate(col_list):
                if cc.is_categorical():
                    idx = binner.bin_categorical(ex.categorical[name])
                else:
                    j = num_index[name]
                    idx = binner.bin_numeric(ex.numeric[:, j],
                                             ex.numeric_valid[:, j])
                idx_mat[:, col_i] = np.asarray(idx, np.int64) + offsets[col_i]
            for u in np.unique(units):
                unit_ids.setdefault(u, len(unit_ids))
            if len(unit_ids) > acc.shape[0]:
                acc = np.vstack([acc, np.zeros(
                    (len(unit_ids) - acc.shape[0], total_bins), np.float64)])
            uvec = np.fromiter((unit_ids[u] for u in units), np.int64,
                               count=len(units))
            flat = uvec[:, None] * total_bins + idx_mat
            counts = np.bincount(flat.ravel(),
                                 minlength=len(unit_ids) * total_bins)
            acc += counts.reshape(len(unit_ids), total_bins)
        if not unit_ids:
            return
        units_sorted = sorted(unit_ids.items(), key=lambda kv: kv[0])
        by_name = {name: ci for ci, (name, _) in enumerate(col_list)}
        for cc in self.column_configs:
            ci = by_name.get(cc.columnName)
            if ci is None:
                continue
            s, e = offsets[ci], offsets[ci + 1]
            overall = acc[:, s:e].sum(axis=0)
            vals = [psi(overall, acc[unit_ids[u], s:e])
                    for u, _ in units_sorted]
            cc.columnStats.psi = _f(np.nanmax(vals)) if vals else None
            cc.columnStats.unitStats = [
                f"{u}:{psi(overall, acc[uid, s:e]):.6f}"
                for u, uid in units_sorted]


def _sig_hash(sig: dict) -> str:
    return hashlib.md5(
        json.dumps(sig, sort_keys=True).encode()).hexdigest()


def _load_partial(path: str, sig_hash: str):
    """(meta, arrays) of a mid-sweep partial, or None when missing, torn,
    or written under a different input/config signature."""
    import zipfile
    try:
        data = np.load(path)
        meta = json.loads(bytes(data["__meta__"]).decode())
        if meta.get("version") != 1 or meta.get("sig") != sig_hash:
            return None
        return meta, {k: data[k] for k in data.files if k != "__meta__"}
    except (OSError, ValueError, KeyError, zipfile.BadZipFile):
        return None


def _f(x) -> Optional[float]:
    if x is None:
        return None
    x = float(x)
    return None if math.isnan(x) or math.isinf(x) else x


def _fl(arr) -> List[Optional[float]]:
    return [(_f(x) if x == x else None) for x in np.asarray(arr, dtype=np.float64)]


def _merge_vals(vals, groups):
    return [sum(vals[i] for i in g) for g in groups]


# appended as a method via assignment below to keep the class block above
# readable (the rebin pass is self-contained)
def _dynamic_rebin(self) -> None:
    """``stats -rebin``: IV-driven merge of adjacent value bins (reference
    ``DynamicBinningUDF`` / ``AutoDynamicBinning``), honoring
    ``-Dshifu.rebin.maxNumBin`` / ``-Dshifu.rebin.ivKeepRatio``."""
    from ..config import environment
    from ..ops.binning import CATEGORY_GROUP_SEP
    from ..ops.stats_math import column_metrics, merge_adjacent_by_iv

    target = int(environment.get_property("shifu.rebin.maxNumBin",
                                          self.model_config.stats.maxNumBin))
    _ivr = self.params.get("rebin_ivr")
    iv_keep = float(_ivr) if _ivr is not None else \
        float(environment.get_property("shifu.rebin.ivKeepRatio", 0.95))
    _bic = self.params.get("rebin_bic")
    min_inst = int(_bic) if _bic is not None else \
        int(environment.get_property("shifu.rebin.minBinInstCnt", 0))
    only = {v.strip() for v in (self.params.get("rebin_vars") or "").split(",")
            if v.strip()}
    from ..config.column_config import ns_in
    merged_cols = 0
    for cc in self.column_configs:
        if only and not ns_in(cc.columnName, only):
            continue
        bn = cc.columnBinning
        if not bn.binCountNeg or len(bn.binCountNeg) < 4:
            continue
        neg, pos = bn.binCountNeg[:-1], bn.binCountPos[:-1]  # drop missing bin
        if cc.is_categorical():
            # order categories by pos rate so "adjacent" is meaningful
            rate = [(p / max(p + n, 1e-9)) for p, n in zip(pos, neg)]
            order = sorted(range(len(rate)), key=lambda i: rate[i])
        else:
            order = list(range(len(neg)))
        groups = merge_adjacent_by_iv(
            np.asarray([neg[i] for i in order], np.float64),
            np.asarray([pos[i] for i in order], np.float64),
            target, iv_keep, min_inst)
        if len(groups) >= len(neg):
            continue
        merged_cols += 1
        groups = [[order[i] for i in g] for g in groups]
        if cc.is_categorical():
            bn.binCategory = [CATEGORY_GROUP_SEP.join(
                bn.binCategory[i] for i in g) for g in groups]
        else:
            bn.binBoundary = [bn.binBoundary[g[0]] for g in groups]
        miss_n, miss_p = bn.binCountNeg[-1], bn.binCountPos[-1]
        wmiss_n, wmiss_p = bn.binWeightedNeg[-1], bn.binWeightedPos[-1]
        bn.binCountNeg = _merge_vals(bn.binCountNeg[:-1], groups) + [miss_n]
        bn.binCountPos = _merge_vals(bn.binCountPos[:-1], groups) + [miss_p]
        bn.binWeightedNeg = _merge_vals(bn.binWeightedNeg[:-1], groups) + [wmiss_n]
        bn.binWeightedPos = _merge_vals(bn.binWeightedPos[:-1], groups) + [wmiss_p]
        bn.length = len(groups) + 1
        neg_a = np.asarray(bn.binCountNeg, np.float64)[None, :]
        pos_a = np.asarray(bn.binCountPos, np.float64)[None, :]
        wneg_a = np.asarray(bn.binWeightedNeg, np.float64)[None, :]
        wpos_a = np.asarray(bn.binWeightedPos, np.float64)[None, :]
        cm = column_metrics(neg_a, pos_a)
        wm = column_metrics(wneg_a, wpos_a)
        tot = neg_a + pos_a
        bn.binPosRate = _fl(np.where(tot > 0, pos_a / np.maximum(tot, 1), np.nan)[0])
        bn.binCountWoe = _fl(cm.bin_woe[0])
        bn.binWeightedWoe = _fl(wm.bin_woe[0])
        st = cc.columnStats
        st.ks, st.iv, st.woe = _f(cm.ks[0]), _f(cm.iv[0]), _f(cm.woe[0])
        st.weightedKs, st.weightedIv = _f(wm.ks[0]), _f(wm.iv[0])
        st.weightedWoe = _f(wm.woe[0])
    log.info("rebin: merged bins in %d columns (target %d, ivKeep %.2f)",
             merged_cols, target, iv_keep)


StatsProcessor._dynamic_rebin = _dynamic_rebin
