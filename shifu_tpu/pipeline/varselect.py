"""`varselect` step — reference ``VarSelectModelProcessor.java:95`` +
``core/VariableSelector.java`` + the sensitivity MR job (``core/varselect/``).

Paths implemented:
- filter-based ranking by KS / IV / MIX / PARETO over the stats already in
  ColumnConfig (``VarSelectModelProcessor.java:181-199``);
- auto-filter: missing-rate, min KS/IV, and pairwise-correlation pruning
  (drop the lower-ranked of any pair above ``correlationThreshold``);
- SE / ST sensitivity: the reference trains an NN then runs an MR job that
  re-scores every record with feature i frozen to its mean
  (``core/varselect/VarSelectMapper.java:93-120``) — here that whole job is
  the STREAMED, mask-batched device program of
  :mod:`shifu_tpu.ops.sensitivity`: the norm plane streams window-by-window
  (never host-resident), each window evaluates ``MaskBatch`` frozen-column
  masks per vmapped launch, scores fetch ONCE at the end; score[i] = MSE
  rise when column i's feature block is frozen;
- force-select / force-remove name files; ``-list`` / ``-reset`` /
  ``-recover`` bookkeeping with a varsel history file.
"""

from __future__ import annotations

import json
import logging
import os
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..config import ColumnConfig
from ..config.model_config import FilterBy
from ..config.validator import ModelStep
from .processor import BasicProcessor

log = logging.getLogger(__name__)


def pareto_front_ranks(ks: np.ndarray, iv: np.ndarray) -> np.ndarray:
    """Iterative Pareto fronts over (ks, iv): rank 0 = first front
    (reference PARETO filter).  Each front computes ONE broadcast
    domination matrix (dominated[i] = any j with k_j>=k_i, v_j>=v_i and a
    strict edge) instead of the former per-point Python scan."""
    n = len(ks)
    remaining = np.arange(n)
    ranks = np.zeros(n, int)
    r = 0
    while len(remaining):
        k, v = ks[remaining], iv[remaining]
        ge = (k[:, None] >= k[None, :]) & (v[:, None] >= v[None, :])
        gt = (k[:, None] > k[None, :]) | (v[:, None] > v[None, :])
        dominated = np.any(ge & gt, axis=0)
        front = remaining[~dominated]
        ranks[front] = r
        remaining = remaining[dominated]
        r += 1
    return ranks


class VarSelectProcessor(BasicProcessor):
    step = ModelStep.VARSELECT

    def process(self) -> int:
        if self.params.get("list"):
            return self._list()
        if self.params.get("reset"):
            return self._reset()
        if self.params.get("recover"):
            return self._recover()
        if self.params.get("autofilter"):
            return self._autofilter_only()
        if self.params.get("recoverauto"):
            return self._recover_auto()
        return self._select()

    # ---------------------------------------------------------- bookkeeping
    def _selected(self) -> List[ColumnConfig]:
        return [c for c in self.column_configs if c.finalSelect]

    def _list(self) -> int:
        for c in self._selected():
            log.info("selected: %3d %s (ks=%.4f iv=%.4f)", c.columnNum,
                     c.columnName, c.columnStats.ks or 0, c.columnStats.iv or 0)
        log.info("%d columns selected", len(self._selected()))
        return 0

    def _reset(self) -> int:
        self._push_history()
        for c in self.column_configs:
            c.finalSelect = False
        self.save_column_configs()
        log.info("selection reset")
        return 0

    @staticmethod
    def _pop_last_history(path: str, what: str, apply_fn) -> bool:
        """Parse the last JSONL entry of a history file, run ``apply_fn``
        on it, and only THEN truncate the file — a failure while parsing
        or applying leaves the undo entry intact for a retry."""
        if not os.path.isfile(path):
            log.error("no %s history to recover from", what)
            return False
        lines = open(path).read().strip().splitlines()
        if not lines:
            log.error("%s history empty", what)
            return False
        apply_fn(json.loads(lines[-1]))
        # atomic truncation: a crash mid-rewrite must not tear the
        # remaining history (the torn-write hazard PR 4 eliminated for
        # every other artifact)
        from ..ioutil import atomic_write_text
        atomic_write_text(path, "\n".join(lines[:-1])
                          + ("\n" if lines[:-1] else ""))
        return True

    def _recover(self) -> int:
        def apply(last):
            sel = set(last["selected"])
            for c in self.column_configs:
                c.finalSelect = c.columnNum in sel
            self.save_column_configs()
            log.info("recovered selection of %d columns (ts %s)",
                     len(sel), last.get("ts"))
        return 0 if self._pop_last_history(
            self.paths.varsel_history_path, "varsel", apply) else 1

    def _push_history(self) -> None:
        os.makedirs(self.paths.varsel_dir, exist_ok=True)
        entry = {"ts": time.strftime("%Y-%m-%d %H:%M:%S"),
                 "selected": [c.columnNum for c in self._selected()]}
        # append-only history ledger: readers tolerate a torn tail
        with open(self.paths.varsel_history_path, "a") as f:  # shifu-lint: disable=atomic-write
            f.write(json.dumps(entry) + "\n")

    # ------------------------------------------------- standalone autofilter
    def _autofilter_only(self) -> int:
        """``varselect -autofilter`` (reference ``ShifuCLI.java:836``):
        apply ONLY the missing-rate/KS/IV/correlation auto filter to the
        currently selected columns, recording what it turned off so
        ``-recoverauto`` can undo it."""
        vs = self.model_config.varSelect
        selected = [c for c in self.column_configs
                    if c.finalSelect and not c.is_force_select()]
        if not selected:
            log.error("no selected columns to auto-filter — run a "
                      "selection first")
            return 1
        kept = {c.columnNum for c in self._auto_filter(selected, vs)}
        removed = [c.columnNum for c in selected if c.columnNum not in kept]
        if not removed:
            log.info("autofilter: nothing to remove (%d columns pass)",
                     len(kept))
            return 0
        for c in selected:
            c.finalSelect = c.columnNum in kept
        os.makedirs(self.paths.varsel_dir, exist_ok=True)
        # append-only history ledger: readers tolerate a torn tail
        with open(self._autofilter_history_path(), "a") as f:  # shifu-lint: disable=atomic-write
            f.write(json.dumps({"ts": time.strftime("%Y-%m-%d %H:%M:%S"),
                                "removed": removed}) + "\n")
        self.save_column_configs()
        log.info("autofilter: %d kept, %d removed", len(kept), len(removed))
        return 0

    def _recover_auto(self) -> int:
        """``varselect -recoverauto``: restore the variables the last
        ``-autofilter`` run turned off (reference ``ShifuCLI.java:837``)."""
        def apply(last):
            removed = set(last["removed"])
            n = 0
            for c in self.column_configs:
                if c.columnNum in removed:
                    c.finalSelect = True
                    n += 1
            self.save_column_configs()
            log.info("recovered %d auto-filtered columns (ts %s)", n,
                     last.get("ts"))
        return 0 if self._pop_last_history(
            self._autofilter_history_path(), "autofilter", apply) else 1

    def _autofilter_history_path(self) -> str:
        return os.path.join(self.paths.varsel_dir, "autofilter.history")

    # ------------------------------------------------------------- selection
    def _check_filterby_algorithm(self) -> None:
        """filterBy vs train.algorithm compatibility (reference
        ``VarSelectModelProcessor.java:188-200``) — checked BEFORE any side
        effect (history push, recursive retrain rounds)."""
        vs = self.model_config.varSelect
        if not vs.filterEnable:
            return
        fb, alg = vs.filterBy, self.model_config.train.algorithm.name
        from ..config.validator import ValidationError
        if fb in (FilterBy.SE, FilterBy.ST) and \
                alg not in ("NN", "LR", "SVM", "TENSORFLOW"):
            raise ValidationError(
                [f"varSelect.filterBy {fb.name} needs an NN/LR model "
                 f"(train.algorithm is {alg}) — use filterBy FI for "
                 "tree models"])
        if fb == FilterBy.FI and alg not in ("GBT", "RF", "DT"):
            raise ValidationError(
                [f"varSelect.filterBy FI needs a tree model "
                 f"(train.algorithm is {alg}) — use SE/ST for NN/LR"])

    def _select(self) -> int:
        vs = self.model_config.varSelect
        self._check_filterby_algorithm()
        rounds = int(self.params.get("recursive") or 1)
        if rounds > 1:
            if vs.filterBy not in (FilterBy.SE, FilterBy.ST):
                log.error("varselect -recursive needs filterBy SE/ST "
                          "(wrapper re-scoring); got %s", vs.filterBy.name)
                return 1
            return self._recursive_select(rounds)
        return self._select_once()

    def _recursive_select(self, rounds: int) -> int:
        """SE/ST wrapper recursion (reference
        ``VarSelectModelProcessor.java:201-227``): each round re-norms and
        retrains on the CURRENT selection, re-scores sensitivity against
        the fresh model, re-selects, and snapshots ``ColumnConfig.json.{i}``
        + ``se.{i}.json`` into varsels/ for audit (reference varsel dir
        history + ``se.x`` copies)."""
        from .norm import NormalizeProcessor
        from .train import TrainProcessor
        os.makedirs(self.paths.varsel_dir, exist_ok=True)
        self._snapshot_round(0)
        for i in range(rounds):
            self.save_column_configs()   # current selection feeds norm/train
            for proc_cls in (NormalizeProcessor, TrainProcessor):
                rc = proc_cls(self.dir, {}).run()
                if rc != 0:
                    log.error("recursive varselect round %d: %s failed "
                              "(rc=%d)", i + 1, proc_cls.__name__, rc)
                    return rc
            rc = self._select_once()
            if rc != 0:
                return rc
            self._snapshot_round(i + 1)
            se_src = os.path.join(self.paths.varsel_dir, "se.json")
            if os.path.isfile(se_src):
                _atomic_copy(se_src, os.path.join(self.paths.varsel_dir,
                                                  f"se.{i}.json"))
            log.info("recursive varselect round %d/%d: %d selected",
                     i + 1, rounds, len(self._selected()))
        return 0

    def _snapshot_round(self, i: int) -> None:
        src = self.paths.column_config_path
        if os.path.isfile(src):
            _atomic_copy(src, os.path.join(self.paths.varsel_dir,
                                           f"ColumnConfig.json.{i}"))

    def _select_once(self) -> int:
        vs = self.model_config.varSelect
        self._push_history()
        self._apply_force_files(vs)
        candidates = [c for c in self.column_configs
                      if c.is_candidate() and not c.is_force_select()
                      and c.columnStats.ks is not None]
        if vs.autoFilterEnable:
            candidates = self._auto_filter(candidates, vs)
        # clear stale selection on every non-forced column first: columns
        # pruned from `candidates` this run must not keep finalSelect from a
        # previous run
        for c in self.column_configs:
            if not c.is_force_select():
                c.finalSelect = False
        if not vs.filterEnable:
            for c in candidates:
                c.finalSelect = True
            self.save_column_configs()
            return 0

        fb = vs.filterBy
        if fb in (FilterBy.SE, FilterBy.ST):
            scores = self._sensitivity_scores(candidates, fb)
        elif fb == FilterBy.GENETIC:
            scores = self._genetic_scores(candidates, vs)
        elif fb == FilterBy.FI:
            scores = self._fi_scores(candidates)
        elif fb == FilterBy.IV:
            scores = {c.columnNum: c.columnStats.iv or 0 for c in candidates}
        elif fb == FilterBy.MIX:
            # MIX: mean of per-metric ranks (reference mixed KS+IV rank)
            ks_rank = _rank_of({c.columnNum: c.columnStats.ks or 0
                                for c in candidates})
            iv_rank = _rank_of({c.columnNum: c.columnStats.iv or 0
                                for c in candidates})
            scores = {k: -(ks_rank[k] + iv_rank[k]) / 2 for k in ks_rank}
        elif fb == FilterBy.PARETO:
            ks = np.array([c.columnStats.ks or 0 for c in candidates])
            iv = np.array([c.columnStats.iv or 0 for c in candidates])
            ranks = pareto_front_ranks(ks, iv)
            scores = {c.columnNum: -float(r)
                      for c, r in zip(candidates, ranks)}
        else:  # KS default
            scores = {c.columnNum: c.columnStats.ks or 0 for c in candidates}

        # -inf marks columns the scoring model never saw (dropped in an
        # earlier recursive round): never selectable, not merely last —
        # and excluded BEFORE the filterOutRatio math so the ratio applies
        # to the selectable set
        candidates = [c for c in candidates
                      if scores[c.columnNum] != float("-inf")]
        n_keep = vs.filterNum
        if vs.filterOutRatio is not None:
            n_keep = min(n_keep,
                         int(len(candidates) * (1 - vs.filterOutRatio)))
        ranked = sorted(candidates, key=lambda c: -scores[c.columnNum])
        keep = set(c.columnNum for c in ranked[:n_keep])
        for c in candidates:
            c.finalSelect = c.columnNum in keep
        self.save_column_configs()
        n_force = sum(1 for c in self.column_configs if c.is_force_select())
        log.info("varselect by %s: %d selected (+%d force), from %d candidates",
                 fb.name, len(keep), n_force, len(candidates))
        return 0

    def _apply_force_files(self, vs) -> None:
        from ..config.column_config import ColumnFlag, ns_in
        force_sel = _read_names(self._abs(vs.forceSelectColumnNameFile))
        force_rem = _read_names(self._abs(vs.forceRemoveColumnNameFile))
        for c in self.column_configs:
            # NSColumn matching: bare names in force files match namespaced
            # header columns (reference column/NSColumn.java equality)
            if ns_in(c.columnName, force_rem):
                c.columnFlag = ColumnFlag.ForceRemove
                c.finalSelect = False
            elif ns_in(c.columnName, force_sel) and c.is_candidate():
                c.columnFlag = ColumnFlag.ForceSelect
                c.finalSelect = True

    def _auto_filter(self, candidates: List[ColumnConfig], vs
                     ) -> List[ColumnConfig]:
        """Missing-rate + min KS/IV + correlation pruning (reference
        autoFilter / ``VarSelectModelProcessor.java:208``)."""
        out = []
        for c in candidates:
            miss = c.columnStats.missingPercentage or 0.0
            if miss > vs.missingRateThreshold:
                continue
            if (c.columnStats.ks or 0) < vs.minKsThreshold:
                continue
            if (c.columnStats.iv or 0) < vs.minIvThreshold:
                continue
            out.append(c)
        dropped = len(candidates) - len(out)
        if vs.correlationThreshold < 1.0:
            out, corr_dropped = self._correlation_prune(out, vs)
            dropped += corr_dropped
        if dropped:
            log.info("auto-filter removed %d columns", dropped)
        return out

    def _correlation_prune(self, cols: List[ColumnConfig], vs
                           ) -> Tuple[List[ColumnConfig], int]:
        corr_path = self.paths.correlation_path
        if not os.path.isfile(corr_path):
            log.warning("correlation matrix missing — run `stats -correlation`"
                        " first; skipping correlation pruning")
            return cols, 0
        # csv written by stats: header row + name-keyed rows
        with open(corr_path) as f:
            header = f.readline().strip().split(",")[1:]
            mat = np.array([[float(v) for v in line.strip().split(",")[1:]]
                            for line in f])
        idx = {n: i for i, n in enumerate(header)}
        ranked = sorted(cols, key=lambda c: -(c.columnStats.ks or 0))
        # index the matrix ONCE per candidate and compare against all kept
        # rows with a numpy mask (the former kept-vs-candidate inner loop
        # was nested dict lookups per pair)
        abs_mat = np.abs(mat)
        kept: List[ColumnConfig] = []
        kept_rows: List[int] = []            # matrix rows of kept columns
        for c in ranked:
            i = idx.get(c.columnName)
            if i is None or not kept_rows or \
                    not np.any(abs_mat[i, kept_rows]
                               > vs.correlationThreshold):
                kept.append(c)
                if i is not None:
                    kept_rows.append(i)
        kept_names = {c.columnName for c in kept}
        return [c for c in cols if c.columnName in kept_names], \
            len(cols) - len(kept)

    # ---------------------------------------------------------- sensitivity
    def _sensitivity_scores(self, candidates: List[ColumnConfig],
                            fb: FilterBy) -> Dict[int, float]:
        """SE/ST: ΔMSE when a column's feature block is frozen to its mean.

        The reference trains one NN then fans out an MR job
        (``VarSelectMapper.java:66``); here the whole job is the streamed,
        mask-batched device program of :mod:`shifu_tpu.ops.sensitivity`:
        the norm plane streams window-by-window (never resident on host),
        each window evaluates ``MaskBatch`` candidate masks per vmapped
        launch, and the scores come back in ONE end-of-job fetch.
        ``-Dshifu.varsel.batched=false`` restores the seed's resident
        per-column loop (the parity oracle)."""
        from .. import obs
        from ..config import environment
        from ..data.shards import Shards
        from ..ioutil import atomic_write_json
        from ..models import nn as nn_model
        from ..ops import sensitivity as sens

        model_path = self.paths.model_path(0, None)
        if not os.path.isfile(model_path):
            raise FileNotFoundError(
                f"{model_path} not found — SE/ST varselect needs a trained "
                "model; run `train` first (reference trains one inline)")
        spec, params = nn_model.load_model(model_path)
        shards = Shards.open(self.paths.norm_dir)
        names = shards.schema["outputNames"]
        col_nums = shards.schema["columnNums"]

        # map candidate column -> its feature indices (onehot/woe blocks,
        # frozen as WHOLE blocks)
        blocks = _column_blocks(names, col_nums, candidates)
        in_plane = [c for c in candidates if blocks.get(c.columnNum)]
        if not in_plane:
            raise RuntimeError("SE/ST varselect: no candidate feature "
                               "blocks in the normalized plane — run `norm`")
        masks = sens.mask_matrix(
            len(names), [blocks[c.columnNum] for c in in_plane])

        t0 = time.perf_counter()
        with obs.span("varselect.sensitivity", kind="phase"):
            if environment.get_bool("shifu.varsel.batched", True):
                n_rows = self._run_streamed_sensitivity(
                    shards, spec, params, masks)
                mse, base_mse = self._sens_result
            else:               # escape hatch: the seed's resident loop
                data = shards.load_all()
                mse, base_mse = sens.per_column_scores(
                    spec, params, data["x"], data["y"], masks)
                n_rows = len(data["y"])
                self._sens_result = (mse, base_mse)
        dt = max(time.perf_counter() - t0, 1e-9)
        obs.gauge("varsel.rows_per_sec").set(n_rows * len(in_plane) / dt)
        obs.gauge("varsel.candidates").set(float(len(in_plane)))
        log.info("sensitivity: %d candidates x %d rows in %.2fs "
                 "(%.0f rows*cols/s)", len(in_plane), n_rows, dt,
                 n_rows * len(in_plane) / dt)

        scores = _scores_from_mse(candidates,
                                  [c.columnNum for c in in_plane],
                                  mse, base_mse, fb)
        os.makedirs(self.paths.varsel_dir, exist_ok=True)
        atomic_write_json(
            os.path.join(self.paths.varsel_dir, "se.json"),
            {str(k): v for k, v in
             sorted(scores.items(), key=lambda kv: -kv[1])
             if v != float("-inf")})
        return scores

    def _run_streamed_sensitivity(self, shards, spec, params,
                                  masks) -> int:
        """Window geometry + stream wiring for the mask-batched job;
        stashes (mse, base_mse) on ``self._sens_result`` and returns the
        row count."""
        from ..data.streaming import ShardStream, stream_window_rows
        from ..ops import sensitivity as sens
        from ..parallel.mesh import device_mesh

        vs = self.model_config.varSelect
        B = sens.mask_batch_size(vs.params)
        mesh = device_mesh()
        d = len(shards.schema["outputNames"])
        # the vmapped launch holds ~B frozen window copies: account B in
        # the row-bytes estimate so the auto window shrinks with the batch
        window_rows = stream_window_rows(4 * (d + 2) * max(1, B // 4),
                                         int(mesh.shape["data"]), shards)
        stream = ShardStream(shards, ("x", "y"), window_rows)
        log.info("sensitivity STREAMED: window %d rows, mask batch %d "
                 "(%d programs/window)", window_rows, B,
                 -(-len(masks) // B))
        mse, base_mse, n_rows = sens.streamed_sensitivity(
            stream, spec, params, masks, mesh=mesh, mask_batch=B)
        self._sens_result = (mse, base_mse)
        return n_rows

    def _genetic_scores(self, candidates: List[ColumnConfig],
                        vs) -> Dict[int, float]:
        """dvarsel wrapper search: a population of column subsets evolves by
        inherit/crossover/mutation, fitness = masked-NN validation loss, all
        candidates trained as one vmapped run (reference ``core/dvarsel/``;
        see ``train/dvarsel.py``).  Needs `norm` to have run.  Data mode
        follows the shared streaming decision (``should_stream``): planes
        past the memory budget evaluate fitness as minibatch scans over
        prepared windows instead of loading the matrix."""
        from ..data.shards import Shards
        from ..data.streaming import (ShardStream, should_stream,
                                      stream_window_rows)
        from ..ioutil import atomic_write_json
        from ..train.dvarsel import (WrapperSettings, genetic_varselect,
                                     genetic_varselect_streamed)

        shards = Shards.open(self.paths.norm_dir)
        names = shards.schema["outputNames"]
        col_nums = shards.schema["columnNums"]
        blocks = _column_blocks(names, col_nums, candidates)
        blocks = {cn: idx for cn, idx in blocks.items() if idx}
        if not blocks:
            raise RuntimeError("genetic varselect: no candidate feature "
                               "blocks in the normalized plane — run `norm`")
        settings = WrapperSettings.from_params(
            vs.params, n_select=min(vs.filterNum, len(blocks)),
            valid_rate=self.model_config.train.validSetRate)
        if should_stream(shards):
            from ..parallel.mesh import device_mesh
            mesh = device_mesh(n_ensemble=settings.population)
            window_rows = stream_window_rows(4 * (len(names) + 2),
                                             int(mesh.shape["data"]),
                                             shards)
            stream = ShardStream(shards, ("x", "y", "w"), window_rows)
            log.info("genetic varselect STREAMED: window %d rows, "
                     "population %d", window_rows, settings.population)
            scores, history = genetic_varselect_streamed(
                stream, blocks, settings, mesh=mesh)
        else:
            data = shards.load_all()
            scores, history = genetic_varselect(
                data["x"], data["y"], data["w"], blocks, settings)
        os.makedirs(self.paths.varsel_dir, exist_ok=True)
        atomic_write_json(
            os.path.join(self.paths.varsel_dir, "genetic.json"),
            {"history": history,
             "credit": {str(k): v for k, v in sorted(
                 scores.items(), key=lambda kv: -kv[1])}})
        # columns with no feature block rank last
        for c in candidates:
            scores.setdefault(c.columnNum, -1.0)
        return scores

    def _fi_scores(self, candidates: List[ColumnConfig]) -> Dict[int, float]:
        """FI filter: posttrain featureImportance output (tree FI or NN
        spread)."""
        fi_path = self.paths.feature_importance_path
        if not os.path.isfile(fi_path):
            raise FileNotFoundError(
                f"{fi_path} not found — FI varselect needs `posttrain` first")
        by_name = {}
        for line in open(fi_path):
            name, v = line.rsplit("\t", 1)
            by_name[name] = float(v)
        return {c.columnNum: by_name.get(c.columnName, 0.0)
                for c in candidates}


def _column_blocks(names: List[str], col_nums: List[int],
                   candidates: List[ColumnConfig]) -> Dict[int, List[int]]:
    """Feature indices per source column: output names are generated per
    column in order, prefixed by the column name (onehot expands)."""
    by_name = {c.columnName: c.columnNum for c in candidates}
    blocks: Dict[int, List[int]] = {}
    for i, n in enumerate(names):
        # output names are the FULL column name (namespaced names included)
        # plus an optional onehot suffix "_k"
        base = n
        if base not in by_name and "_" in base:
            stem = base.rsplit("_", 1)[0]
            if stem in by_name and base.rsplit("_", 1)[1].isdigit():
                base = stem
        cn = by_name.get(base)
        if cn is not None:
            blocks.setdefault(cn, []).append(i)
    return blocks


def _scores_from_mse(candidates: List[ColumnConfig],
                     in_plane_ids: List[int], mse: np.ndarray,
                     base_mse: float, fb: FilterBy) -> Dict[int, float]:
    """Frozen-MSE vector -> per-column SE/ST scores.  Candidates absent
    from the trained model's feature plane (e.g. dropped in an earlier
    recursive round) score ``-inf``: never selectable, not merely last —
    a 0.0 would outrank in-model columns with negative sensitivity and
    re-select a column the scoring model never saw."""
    scores = {c.columnNum: float("-inf") for c in candidates}
    for cn, m in zip(in_plane_ids, mse):
        # SE: absolute sensitivity; ST: relative rise over base
        scores[cn] = (float(m) - base_mse) if fb == FilterBy.SE \
            else (float(m) - base_mse) / max(base_mse, 1e-12)
    return scores


def _atomic_copy(src: str, dst: str) -> None:
    """Whole-or-nothing snapshot copy (``shutil.copy`` can leave a torn
    destination on a crash mid-write)."""
    from ..ioutil import atomic_write_bytes
    with open(src, "rb") as f:
        atomic_write_bytes(dst, f.read())


def _rank_of(scores: Dict[int, float]) -> Dict[int, int]:
    order = sorted(scores, key=lambda k: -scores[k])
    return {k: i for i, k in enumerate(order)}


def _read_names(path: Optional[str]) -> set:
    from ..config.column_config import read_column_name_file
    return read_column_name_file(path)
