"""`export` step — reference ``ExportModelProcessor.java:70-163``:
``pmml | columnstats | woemapping | corr | woe | bagging``.
"""

from __future__ import annotations

import csv
import logging
import os
from typing import List

from .. import ioutil
from ..config.model_config import Algorithm
from ..config.validator import ModelStep
from .processor import BasicProcessor

log = logging.getLogger(__name__)


class ExportProcessor(BasicProcessor):
    step = ModelStep.EXPORT

    def process(self) -> int:
        t = (self.params.get("type") or "pmml").lower()
        os.makedirs(self.paths.export_dir, exist_ok=True)
        if t in ("pmml", "baggingpmml"):
            # pmml already walks EVERY bagged member (model0..B) — the
            # reference's separate baggingpmml path collapses into it
            # (ExportModelProcessor.java:76-84)
            return self._export_pmml()
        if t == "bagging":
            return self._export_bagging()
        if t == "columnstats":
            return self._export_columnstats()
        if t in ("woemapping", "woe"):
            return self._export_woe()
        if t == "corr":
            return self._export_corr()
        if t in ("spec", "ref", "reference"):
            return self._export_reference_spec()
        log.error("unknown export type %s", t)
        return 1

    def _export_reference_spec(self) -> int:
        """`export -t spec`: emit every trained member in the reference's
        own serialized formats — Encog-EG ``model*.nn`` and
        ``BinaryDTSerializer`` ``model*.gbt``/``model*.rf`` — so the
        reference's dependency-free Java consumers (``IndependentNNModel``,
        ``IndependentTreeModel``, ``shifu convert``) load them unchanged
        (reference model-spec layer, ``BinaryDTSerializer.java:60-160``)."""
        from ..eval.scorer import discover_model_paths
        from ..export import reference_spec as ref
        from ..models import load_any
        paths = discover_model_paths(self.paths.models_dir)
        if not paths:
            log.error("no models to export — run `train` first")
            return 1
        out_dir = os.path.join(self.paths.export_dir, "reference")
        os.makedirs(out_dir, exist_ok=True)
        n = 0
        for i, p in enumerate(paths):
            m = load_any(p)
            kind = type(m).__name__
            try:
                if kind == "IndependentNNModel":
                    out = os.path.join(out_dir, f"model{i}.nn")
                    ref.write_encog_nn(out, m.spec, m.params)
                elif kind == "IndependentTreeModel":
                    suffix = "gbt" if m.spec.algorithm == "GBT" else "rf"
                    out = os.path.join(out_dir, f"model{i}.{suffix}")
                    ref.write_reference_tree(out, m.spec, m.trees,
                                             self.column_configs)
                elif kind == "IndependentWDLModel":
                    out = os.path.join(out_dir, f"model{i}.wdl")
                    ref.write_reference_wdl(out, m.spec, m.params,
                                            self.column_configs)
                else:
                    log.warning("model %s (%s): no reference format; "
                                "skipped", p, kind)
                    continue
            except Exception as e:
                log.error("reference export of %s failed: %s", p, e)
                return 1
            log.info("reference spec -> %s", out)
            n += 1
        if n == 0:
            log.error("reference export: no model had a reference format")
            return 1
        log.info("reference export: %d model(s) -> %s", n, out_dir)
        return 0

    def _export_bagging(self) -> int:
        """Bundle all bagged members + an ensemble manifest into export/
        (reference EXPORT_BAGGING: one spec that scores the whole
        ensemble)."""
        import json as _json
        import shutil

        from ..eval.scorer import discover_model_paths
        paths = discover_model_paths(self.paths.models_dir)
        if not paths:
            log.error("no models to export — run `train` first")
            return 1
        out_dir = os.path.join(self.paths.export_dir, "bagging")
        os.makedirs(out_dir, exist_ok=True)
        members = []
        for p in paths:
            shutil.copy(p, os.path.join(out_dir, os.path.basename(p)))
            members.append(os.path.basename(p))
        sel = self.model_config.evals[0].performanceScoreSelector \
            if self.model_config.evals else "mean"
        ioutil.atomic_write_json(
            os.path.join(out_dir, "ensemble.json"),
            {"modelSet": self.model_config.basic.name,
             "members": members, "scoreSelector": sel or "mean"})
        log.info("bagging export: %d member(s) -> %s", len(members), out_dir)
        return 0

    def _export_pmml(self) -> int:
        from ..export import pmml as pmml_mod
        from ..models import spec_kind
        import glob
        mc = self.model_config
        columns = [c for c in self.column_configs
                   if (c.finalSelect or c.is_force_select()) and c.is_candidate()]
        if not columns:
            columns = [c for c in self.column_configs
                       if c.is_candidate() and c.num_bins() > 0]
        paths = sorted(p for p in glob.glob(
            os.path.join(self.paths.models_dir, "model*.*"))
            if not p.endswith(".json"))
        if not paths:
            log.error("no models to export — run `train` first")
            return 1
        from ..export.pmml import PmmlUnsupportedError
        # reference `export -c`: concise PMML trims the per-bin stats
        # extensions (ShifuCLI.java:366, ModelStatsCreator isConcise)
        concise = bool(self.params.get("concise"))
        for i, mp in enumerate(paths):
            kind = spec_kind(mp)
            try:
                if kind == "tree":
                    from ..models import tree as tree_model
                    spec, trees = tree_model.load_model(mp)
                    doc = pmml_mod.tree_to_pmml(mc, columns, spec, trees,
                                                concise=concise)
                elif kind == "wdl":
                    raise PmmlUnsupportedError(
                        "WDL (embedding) models have no PMML mapping yet — "
                        "use the native .wdl spec")
                elif kind == "svm":
                    raise PmmlUnsupportedError(
                        "kernel SVM models have no PMML mapping (the "
                        "reference's PMML layer covers NN/LR/trees only) — "
                        "use the native .svm spec")
                else:
                    from ..models import nn as nn_model
                    spec, params = nn_model.load_model(mp)
                    if spec.hidden_nodes:
                        doc = pmml_mod.nn_to_pmml(mc, columns, spec, params,
                                                  concise=concise)
                    else:
                        doc = pmml_mod.lr_to_pmml(mc, columns, spec, params,
                                                  concise=concise)
            except PmmlUnsupportedError as e:
                log.error("pmml export of %s failed: %s", mp, e)
                return 1
            out = self.paths.pmml_path(i)
            pmml_mod.write_pmml(doc, out)
            log.info("pmml -> %s", out)
        return 0

    def _export_columnstats(self) -> int:
        out = os.path.join(self.paths.export_dir, "columnstats.csv")
        cols = ["columnNum", "columnName", "columnType", "columnFlag",
                "finalSelect", "max", "min", "mean", "median", "stdDev",
                "missingPercentage", "totalCount", "distinctCount", "ks",
                "iv", "woe", "weightedKs", "weightedIv", "weightedWoe", "psi",
                "skewness", "kurtosis"]
        with ioutil.atomic_open(out, newline="") as f:
            w = csv.writer(f)
            w.writerow(cols)
            for cc in self.column_configs:
                st = cc.columnStats
                w.writerow([cc.columnNum, cc.columnName, cc.columnType.value,
                            cc.columnFlag.value if cc.columnFlag else "",
                            cc.finalSelect, st.max, st.min, st.mean, st.median,
                            st.stdDev, st.missingPercentage, st.totalCount,
                            st.distinctCount, st.ks, st.iv, st.woe,
                            st.weightedKs, st.weightedIv, st.weightedWoe,
                            st.psi, st.skewness, st.kurtosis])
        log.info("columnstats -> %s", out)
        return 0

    def _export_woe(self) -> int:
        out = os.path.join(self.paths.export_dir, "woemapping.csv")
        with ioutil.atomic_open(out, newline="") as f:
            w = csv.writer(f)
            w.writerow(["columnNum", "columnName", "bin", "binLabel",
                        "countWoe", "weightedWoe"])
            for cc in self.column_configs:
                bn = cc.columnBinning
                if not bn.binCountWoe:
                    continue
                labels = (bn.binCategory if cc.is_categorical()
                          else _interval_labels(bn.binBoundary or []))
                labels = list(labels) + ["MISSING"]
                for i, woe in enumerate(bn.binCountWoe):
                    lab = labels[i] if i < len(labels) else f"bin{i}"
                    ww = (bn.binWeightedWoe or [None] * len(bn.binCountWoe))[i]
                    w.writerow([cc.columnNum, cc.columnName, i, lab, woe, ww])
        log.info("woemapping -> %s", out)
        return 0

    def _export_corr(self) -> int:
        src = self.paths.correlation_path
        if not os.path.isfile(src):
            log.error("no correlation matrix — run `stats -correlation` first")
            return 1
        out = os.path.join(self.paths.export_dir, "correlation.csv")
        with open(src) as fi, ioutil.atomic_open(out) as fo:
            fo.write(fi.read())
        log.info("correlation -> %s", out)
        return 0


def _interval_labels(bounds: List[float]) -> List[str]:
    labels = []
    for i, b in enumerate(bounds):
        hi = bounds[i + 1] if i + 1 < len(bounds) else float("inf")
        labels.append(f"[{b:.6g}, {hi:.6g})")
    return labels
