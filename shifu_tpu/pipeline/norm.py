"""`norm` step: materialize normalized + binned training shards.

Replaces reference ``NormalizeModelProcessor.java:48,67-95`` +
``Normalize.pig`` + ``NormalizeUDF``: streams the training data through the
DatasetTransformer and writes npz shards of (x float32, bins int32, target,
weight) to ``tmp/NormalizedData`` / ``tmp/CleanedData``, plus a schema json.
The optional ``-shuffle`` reshuffles rows across shards (reference
``MapReduceShuffle``).

Crash consistency: every shard pair commits atomically (tmp + rename)
and lands a per-shard record in the step journal
(``tmp/journal/NORMALIZE.json``).  A re-run after a crash verifies the
committed prefix against the journal (sizes must match — truncated
committed-looking files drop out) and resumes writing at the first
uncommitted shard; the transform replay is deterministic (per-chunk
sampling substreams), so resumed shard bytes are identical to an
uninterrupted run's.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import time
from typing import Dict, List, Optional

import numpy as np

from .. import faults, obs
from ..config.validator import ModelStep
from ..data import DataSource, sample_mask
from ..data.parsepool import iter_extracted
from ..data.shards import bins_wire_dtype
from ..data.spill import WireWriter, wire_dir
from ..data.transform import DatasetTransformer
from ..ioutil import atomic_savez, atomic_write_json
from .processor import BasicProcessor

log = logging.getLogger(__name__)

SHARD_ROWS = 1 << 18
WIRE_KEYS = ("bins", "y", "w")


class NormalizeProcessor(BasicProcessor):
    step = ModelStep.NORMALIZE

    def process(self) -> int:
        mc = self.model_config
        transformer = DatasetTransformer(mc, self.column_configs)
        source = DataSource(self._abs(mc.dataSet.dataPath), mc.dataSet.dataDelimiter,
                            header_path=self._abs(mc.dataSet.headerPath),
                            header_delimiter=mc.dataSet.headerDelimiter)
        norm_dir, clean_dir = self.paths.norm_dir, self.paths.clean_dir

        # ---- resume: verified committed-shard prefix from a torn run.
        # -shuffle rewrites every shard at the end, so mid-step resume
        # is meaningless there (the journal resets and the run is clean).
        do_shuffle = bool(self.params.get("shuffle"))
        from ..config import environment
        # direct-to-wire: the clean plane lands as the flat spill layout
        # train consumes (no clean npz at all) — the cold train sweep
        # does zero zip decode and zero write-through pass.  -shuffle
        # falls back to npz (it rewrites every shard at the end anyway).
        wire_only = environment.get_bool("shifu.norm.wireOnly", True) \
            and not do_shuffle
        sig = self._signature(source, wire_only)
        items = self.journal.arm(sig, resume=not do_shuffle)
        committed: Dict[int, dict] = {}
        for name, meta in items.items():
            if name.startswith("shard-"):
                committed[int(name.split("-", 1)[1])] = meta
        resume_upto = 0                 # first uncommitted shard index
        while resume_upto in committed:
            resume_upto += 1

        # compact bins storage: the narrowest dtype the ColumnConfig bin
        # space fits (uint8 for <=256 bins) — the same wire format the
        # trainers ship to the device, so clean shards decode AND transfer
        # without a cast
        n_bins = max((c.num_bins() + 1 for c in transformer.columns),
                     default=2)
        self._bins_dtype = bins_wire_dtype(n_bins)
        wire_sig = {"norm": hashlib.md5(
            json.dumps(sig, sort_keys=True).encode()).hexdigest()}
        wdir = wire_dir(clean_dir, WIRE_KEYS)
        wire_dtypes = {"bins": self._bins_dtype,
                       "y": np.dtype(np.float32), "w": np.dtype(np.float32)}
        wire_trailing = {"bins": (len(transformer.columns),),
                         "y": (), "w": ()}
        wire: Optional[WireWriter] = None
        if wire_only and resume_upto:
            # adopt the committed wire prefix (truncating any torn tail);
            # unusable wire state ⇒ the resume is void — restart clean so
            # npz journal records never point at missing wire rows
            wire = WireWriter.resume(wdir, WIRE_KEYS, wire_dtypes,
                                     wire_trailing, wire_sig, resume_upto)
            if wire is None:
                log.warning("norm: journal offers %d committed shard(s) "
                            "but the wire plane does not cover them — "
                            "restarting from shard 0", resume_upto)
                committed, resume_upto = {}, 0
        keep_names = {f"part-{k:05d}.npz" for k in range(resume_upto)}
        for d in (norm_dir, clean_dir):
            os.makedirs(d, exist_ok=True)
            for f in os.listdir(d):
                if f in keep_names:
                    continue
                p = os.path.join(d, f)
                if wire is not None and d == clean_dir \
                        and f == ".spill_cache":
                    # the adopted wire prefix lives here — clear only its
                    # siblings (stale spills over the old npz)
                    for g in os.listdir(p):
                        gp = os.path.join(p, g)
                        if gp != wdir:
                            shutil.rmtree(gp) if os.path.isdir(gp) \
                                else os.remove(gp)
                    continue
                # subdirs too: a previous train left its .spill_cache here
                shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)
        if wire_only and wire is None:
            wire = WireWriter(wdir, WIRE_KEYS, wire_dtypes, wire_trailing,
                              wire_sig)
        if resume_upto:
            obs.counter("norm.resumed_shards").inc(resume_upto)
            log.info("norm: resuming — %d committed shard(s) verified, "
                     "restart at shard %d", resume_upto, resume_upto)

        self._shard_counts: List[int] = []
        self._resume_upto = resume_upto
        self._committed = committed
        self._wire = wire

        rate = mc.normalize.sampleRate
        neg_only = mc.normalize.sampleNegOnly
        shard, rows, seen, total_out = 0, 0, 0, 0
        bufx, bufb, bufy, bufw = [], [], [], []
        # streaming drift monitor (obs/drift): per-column PSI of THIS
        # run's binned windows vs the training-time binning snapshot in
        # ColumnConfig — on a refresh over new data windows this is the
        # drift signal; None (zero per-chunk cost) when telemetry is off
        drift = obs.start_drift_monitor(transformer.columns)
        t0 = time.perf_counter()
        with self.phase("transform") as ph:
            # one-parse plane: pooled parallel parse on a cold raw plane,
            # mmap replay of the columnar raw cache when stats already
            # paid for the parse (zero string-plane touch)
            for ci, ex in iter_extracted(
                    source, transformer.extractor,
                    cache_root=self.paths.raw_cache_dir):
                tc = transformer.transform_extracted(ex)
                if tc.n == 0:
                    continue
                if drift is not None:
                    drift.update(tc.bins)
                keep = sample_mask(tc.n, rate, seed=seen, neg_only=neg_only,
                                   targets=tc.target)
                seen += tc.n
                bufx.append(tc.x[keep]); bufb.append(tc.bins[keep])
                bufy.append(tc.target[keep]); bufw.append(tc.weight[keep])
                rows += int(keep.sum())
                total_out += int(keep.sum())
                if rows >= SHARD_ROWS:
                    self._flush(norm_dir, clean_dir, shard, bufx, bufb,
                                bufy, bufw)
                    shard += 1; rows = 0
                    bufx, bufb, bufy, bufw = [], [], [], []
            if rows:
                self._flush(norm_dir, clean_dir, shard, bufx, bufb, bufy,
                            bufw)
                shard += 1
            ph.set(rows=total_out)
        if wire is not None:
            wire.finish()
        if do_shuffle:
            with self.phase("shuffle"):
                self._shard_counts = self._shuffle(norm_dir) \
                    or self._shard_counts
                self._shuffle(clean_dir)
                self._recommit_shuffled(norm_dir, clean_dir,
                                        self._shard_counts)
        obs.counter("norm.rows").inc(total_out)
        obs.gauge("norm.shards").set(shard)
        obs.gauge("norm.rows_per_sec").set(
            total_out / max(time.perf_counter() - t0, 1e-9))
        if drift is not None:
            drift.emit(path=self.paths.drift_path)
        schema = {
            "outputNames": transformer.output_names,
            "columnNums": [c.columnNum for c in transformer.columns],
            "columnNames": [c.columnName for c in transformer.columns],
            "normType": mc.normalize.normType.name,
            "numShards": shard,
            "numRows": total_out,
            # per-shard row counts: Shards.num_rows / the spill cache read
            # these instead of decoding every npz just to count rows
            "shardRows": list(self._shard_counts),
            "binsDtype": np.dtype(self._bins_dtype).name,
            "width": transformer.width,
        }
        atomic_write_json(os.path.join(norm_dir, "schema.json"), schema)
        clean_schema = dict(schema)
        if wire is not None:
            # the clean plane is wire-backed: Shards.open serves it as
            # mmap slices; the signature pins schema <-> spill manifest
            clean_schema.update(wire=True, wireKeys=list(WIRE_KEYS),
                                wireSignature=wire_sig)
        atomic_write_json(os.path.join(clean_dir, "schema.json"),
                          clean_schema)
        log.info("norm: %d shards, %d input cols -> %d features",
                 shard, len(transformer.columns), transformer.width)
        return 0

    def _signature(self, source: DataSource,
                   wire_only: bool = False) -> dict:
        """Identity of the run's inputs + transform config — a resume is
        only valid when the replayed stream produces the same bytes."""
        mc = self.model_config
        files = []
        for f in source.files:
            try:
                st = os.stat(f)
                files.append([os.path.basename(f), st.st_size,
                              st.st_mtime_ns])
            except OSError:                    # remote URL: pin by name
                files.append([f, None, None])
        try:
            with open(self.paths.column_config_path, "rb") as f:
                cc_hash = hashlib.md5(f.read()).hexdigest()
        except OSError:
            cc_hash = None
        return {"files": files, "columnConfig": cc_hash,
                "normType": mc.normalize.normType.name,
                "sampleRate": mc.normalize.sampleRate,
                "sampleNegOnly": bool(mc.normalize.sampleNegOnly),
                "shardRows": SHARD_ROWS,
                # npz-committed shards cannot resume into a wire run (or
                # vice versa) — mode flips reset the journal
                "wireOnly": bool(wire_only)}

    def _flush(self, norm_dir: str, clean_dir: str, shard: int,
               bufx: List[np.ndarray], bufb, bufy, bufw) -> None:
        x = np.concatenate(bufx); b = np.concatenate(bufb)
        y = np.concatenate(bufy); w = np.concatenate(bufw)
        np_path = os.path.join(norm_dir, f"part-{shard:05d}.npz")
        cl_path = os.path.join(clean_dir, f"part-{shard:05d}.npz")
        prev = self._committed.get(shard) if shard < self._resume_upto \
            else None
        if prev is not None and int(prev.get("rows", -1)) == len(y):
            # verified committed shard from the interrupted run: the
            # deterministic replay reproduced the same row count, so the
            # bytes on disk are the bytes this flush would write — skip
            # the write, keep the commit record
            self._shard_counts.append(int(len(y)))
            if self._wire is not None and self._wire.n_shards <= shard:
                # an earlier divergence truncated the wire behind the
                # journal — re-land this committed shard's rows
                faults.fire("norm", "wire", shard, path=cl_path)
                self._wire.append({"bins": b.astype(self._bins_dtype),
                                   "y": y, "w": w})
            return
        if prev is not None:
            log.warning("norm resume: shard %d row count diverged "
                        "(journal %s vs replay %d) — rewriting",
                        shard, prev.get("rows"), len(y))
        faults.fire("norm", "shard", shard, path=np_path)
        atomic_savez(np_path, x=x, y=y, w=w)
        if self._wire is not None:
            if self._wire.n_shards > shard:
                # divergent resumed shard: it and everything after re-run
                self._wire.truncate_to(shard)
            faults.fire("norm", "wire", shard, path=cl_path)
            self._wire.append({"bins": b.astype(self._bins_dtype),
                               "y": y, "w": w})
            files = [np_path]
        else:
            atomic_savez(cl_path, bins=b.astype(self._bins_dtype), y=y, w=w)
            files = [np_path, cl_path]
        self.journal.commit_item(f"shard-{shard:05d}",
                                 files=files, rows=int(len(y)))
        self._shard_counts.append(int(len(y)))

    def _shuffle(self, d: str) -> Optional[List[int]]:
        """Load all shards, permute rows globally, rewrite (reference
        ``core/shuffle/MapReduceShuffle.java``).  Returns the rewritten
        per-shard row counts (array_split re-balances them)."""
        files = sorted(f for f in os.listdir(d) if f.endswith(".npz"))
        if not files:
            return None
        datas = [dict(np.load(os.path.join(d, f))) for f in files]
        keys = datas[0].keys()
        merged = {k: np.concatenate([dd[k] for dd in datas]) for k in keys}
        n = len(next(iter(merged.values())))
        perm = np.random.default_rng(12345).permutation(n)
        splits = np.array_split(np.arange(n), len(files))
        for i, f in enumerate(files):
            sel = perm[splits[i]]
            atomic_savez(os.path.join(d, f),
                         **{k: merged[k][sel] for k in keys})
        return [len(s) for s in splits]

    def _recommit_shuffled(self, norm_dir: str, clean_dir: str,
                           counts: List[int]) -> None:
        """Shuffle rewrote every shard — re-pin the journal records to
        the shuffled sizes so downstream verification stays truthful."""
        for k, rows in enumerate(counts):
            name = f"part-{k:05d}.npz"
            self.journal.commit_item(
                f"shard-{k:05d}",
                files=[os.path.join(norm_dir, name),
                       os.path.join(clean_dir, name)],
                rows=int(rows), shuffled=True)
