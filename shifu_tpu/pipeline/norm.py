"""`norm` step: materialize normalized + binned training shards.

Replaces reference ``NormalizeModelProcessor.java:48,67-95`` +
``Normalize.pig`` + ``NormalizeUDF``: streams the training data through the
DatasetTransformer and writes npz shards of (x float32, bins int32, target,
weight) to ``tmp/NormalizedData`` / ``tmp/CleanedData``, plus a schema json.
The optional ``-shuffle`` reshuffles rows across shards (reference
``MapReduceShuffle``).
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
from typing import List, Optional

import numpy as np

from .. import obs
from ..config.validator import ModelStep
from ..data import DataSource, sample_mask
from ..data.shards import bins_wire_dtype
from ..data.transform import DatasetTransformer
from .processor import BasicProcessor

log = logging.getLogger(__name__)

SHARD_ROWS = 1 << 18


class NormalizeProcessor(BasicProcessor):
    step = ModelStep.NORMALIZE

    def process(self) -> int:
        mc = self.model_config
        transformer = DatasetTransformer(mc, self.column_configs)
        source = DataSource(self._abs(mc.dataSet.dataPath), mc.dataSet.dataDelimiter,
                            header_path=self._abs(mc.dataSet.headerPath),
                            header_delimiter=mc.dataSet.headerDelimiter)
        norm_dir, clean_dir = self.paths.norm_dir, self.paths.clean_dir
        for d in (norm_dir, clean_dir):
            os.makedirs(d, exist_ok=True)
            for f in os.listdir(d):
                p = os.path.join(d, f)
                # subdirs too: a previous train left its .spill_cache here
                shutil.rmtree(p) if os.path.isdir(p) else os.remove(p)

        # compact bins storage: the narrowest dtype the ColumnConfig bin
        # space fits (uint8 for <=256 bins) — the same wire format the
        # trainers ship to the device, so clean shards decode AND transfer
        # without a cast
        n_bins = max((c.num_bins() + 1 for c in transformer.columns),
                     default=2)
        self._bins_dtype = bins_wire_dtype(n_bins)
        self._shard_counts: List[int] = []

        rate = mc.normalize.sampleRate
        neg_only = mc.normalize.sampleNegOnly
        shard, rows, seen, total_out = 0, 0, 0, 0
        bufx, bufb, bufy, bufw = [], [], [], []
        t0 = time.perf_counter()
        with self.phase("transform") as ph:
            for chunk in source.iter_chunks():
                tc = transformer.transform(chunk)
                if tc.n == 0:
                    continue
                keep = sample_mask(tc.n, rate, seed=seen, neg_only=neg_only,
                                   targets=tc.target)
                seen += tc.n
                bufx.append(tc.x[keep]); bufb.append(tc.bins[keep])
                bufy.append(tc.target[keep]); bufw.append(tc.weight[keep])
                rows += int(keep.sum())
                total_out += int(keep.sum())
                if rows >= SHARD_ROWS:
                    self._flush(norm_dir, clean_dir, shard, bufx, bufb,
                                bufy, bufw)
                    shard += 1; rows = 0
                    bufx, bufb, bufy, bufw = [], [], [], []
            if rows:
                self._flush(norm_dir, clean_dir, shard, bufx, bufb, bufy,
                            bufw)
                shard += 1
            ph.set(rows=total_out)
        if self.params.get("shuffle"):
            with self.phase("shuffle"):
                self._shard_counts = self._shuffle(norm_dir) \
                    or self._shard_counts
                self._shuffle(clean_dir)
        obs.counter("norm.rows").inc(total_out)
        obs.gauge("norm.shards").set(shard)
        obs.gauge("norm.rows_per_sec").set(
            total_out / max(time.perf_counter() - t0, 1e-9))
        schema = {
            "outputNames": transformer.output_names,
            "columnNums": [c.columnNum for c in transformer.columns],
            "columnNames": [c.columnName for c in transformer.columns],
            "normType": mc.normalize.normType.name,
            "numShards": shard,
            "numRows": total_out,
            # per-shard row counts: Shards.num_rows / the spill cache read
            # these instead of decoding every npz just to count rows
            "shardRows": list(self._shard_counts),
            "binsDtype": np.dtype(self._bins_dtype).name,
            "width": transformer.width,
        }
        with open(os.path.join(norm_dir, "schema.json"), "w") as f:
            json.dump(schema, f, indent=2)
        with open(os.path.join(clean_dir, "schema.json"), "w") as f:
            json.dump(schema, f, indent=2)
        log.info("norm: %d shards, %d input cols -> %d features",
                 shard, len(transformer.columns), transformer.width)
        return 0

    def _flush(self, norm_dir: str, clean_dir: str, shard: int,
               bufx: List[np.ndarray], bufb, bufy, bufw) -> None:
        x = np.concatenate(bufx); b = np.concatenate(bufb)
        y = np.concatenate(bufy); w = np.concatenate(bufw)
        np.savez(os.path.join(norm_dir, f"part-{shard:05d}.npz"),
                 x=x, y=y, w=w)
        np.savez(os.path.join(clean_dir, f"part-{shard:05d}.npz"),
                 bins=b.astype(self._bins_dtype), y=y, w=w)
        self._shard_counts.append(int(len(y)))

    def _shuffle(self, d: str) -> Optional[List[int]]:
        """Load all shards, permute rows globally, rewrite (reference
        ``core/shuffle/MapReduceShuffle.java``).  Returns the rewritten
        per-shard row counts (array_split re-balances them)."""
        files = sorted(f for f in os.listdir(d) if f.endswith(".npz"))
        if not files:
            return None
        datas = [dict(np.load(os.path.join(d, f))) for f in files]
        keys = datas[0].keys()
        merged = {k: np.concatenate([dd[k] for dd in datas]) for k in keys}
        n = len(next(iter(merged.values())))
        perm = np.random.default_rng(12345).permutation(n)
        splits = np.array_split(np.arange(n), len(files))
        for i, f in enumerate(files):
            sel = perm[splits[i]]
            np.savez(os.path.join(d, f), **{k: merged[k][sel] for k in keys})
        return [len(s) for s in splits]

