"""`new` + `init` steps.

Reference: ``CreateModelProcessor.java`` (scaffold a model-set dir with a
template ModelConfig.json) and ``InitModelProcessor.java:74,89`` (build the
initial ColumnConfig.json from the header, with auto-type inference standing
in for the reference's HyperLogLog distinct-count MR job,
``InitModelProcessor.java:334-347``).
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

import numpy as np
import pandas as pd

from ..config import (ColumnConfig, ColumnFlag, ColumnType, ModelConfig,
                      build_initial_column_configs, save_column_configs)
from ..config.validator import ModelStep
from ..data import DataSource, parse_numeric
from .processor import BasicProcessor

log = logging.getLogger(__name__)


def create_new_model(name: str, base_dir: str = ".", algorithm: str = "NN",
                     description: Optional[str] = None) -> str:
    """``shifu-tpu new <name>``: scaffold the model-set directory
    (reference ``new -t <alg> -m <description>``)."""
    model_dir = os.path.join(base_dir, name)
    os.makedirs(model_dir, exist_ok=True)
    mc_path = os.path.join(model_dir, "ModelConfig.json")
    if os.path.isfile(mc_path):
        raise FileExistsError(f"{mc_path} already exists")
    mc = ModelConfig.create(name, description)
    from ..config.jsonbean import parse_enum
    from ..config.model_config import Algorithm
    mc.train.algorithm = parse_enum(Algorithm, algorithm)
    mc.save(mc_path)
    log.info("created model set at %s", model_dir)
    return model_dir


# per-algorithm train#params defaults (reference `shifu init -model`,
# ``BasicModelProcessor.java:404-500`` checkAlgorithmParam): when the
# sentinel key is absent the whole params map is replaced and saved
_ALG_DEFAULT_PARAMS = {
    "LR": ("LearningRate", {"LearningRate": 0.1}),
    "NN": ("Propagation", {"Propagation": "R", "LearningRate": 0.1,
                           "NumHiddenLayers": 2, "NumHiddenNodes": [20, 10],
                           "ActivationFunc": ["tanh", "tanh"]}),
    "SVM": ("Kernel", {"Kernel": "linear", "Gamma": 1.0, "Const": 1.0}),
    "RF": ("MaxDepth", {"TreeNum": 10,
                        "FeatureSubsetStrategy": "TWOTHIRDS",
                        "MaxDepth": 14, "MinInstancesPerNode": 1,
                        "MinInfoGain": 0.0, "Impurity": "entropy",
                        "Loss": "squared"}),
    "GBT": ("MaxDepth", {"TreeNum": 100,
                         "FeatureSubsetStrategy": "TWOTHIRDS",
                         "MaxDepth": 7, "MinInstancesPerNode": 5,
                         "MinInfoGain": 0.0, "DropoutRate": 0.0,
                         "Impurity": "variance", "LearningRate": 0.05,
                         "Loss": "squared"}),
}


def check_algorithm_param(model_dir: str) -> int:
    """``shifu init -model``: fill the configured algorithm's default
    train#params when they are missing and save ModelConfig.json
    (reference ``ShifuCLI.java:632`` → checkAlgorithmParam).  DT /
    TENSORFLOW / WDL take no defaults, like the reference."""
    import logging
    import os

    from ..config.model_config import ModelConfig

    log = logging.getLogger(__name__)
    mc_path = os.path.join(model_dir, "ModelConfig.json")
    mc = ModelConfig.load(mc_path)
    alg = (mc.train.algorithm.value if hasattr(mc.train.algorithm, "value")
           else str(mc.train.algorithm)).upper()
    entry = _ALG_DEFAULT_PARAMS.get(alg)
    if entry is None:
        if alg in ("DT", "TENSORFLOW", "WDL", "GENERIC"):
            log.info("init -model: no defaults for %s (reference parity)",
                     alg)
            return 0
        log.error("init -model: unsupported algorithm %s", alg)
        return 1
    sentinel, defaults = entry
    params = dict(mc.train.params or {})
    if sentinel in params:
        log.info("init -model: %s params already set (%s present)", alg,
                 sentinel)
        return 0
    mc.train.params = dict(defaults)
    if alg == "GBT":   # the reference also widens the epoch budget for GBT
        mc.train.numTrainEpochs = 10000
    mc.save(mc_path)
    log.info("init -model: filled %s default params into ModelConfig.json",
             alg)
    return 0


def _read_column_file(path: Optional[str], base_dir: str) -> List[str]:
    if not path:
        return []
    p = path if os.path.isabs(path) else os.path.join(base_dir, path)
    if not os.path.isfile(p):
        return []
    out = []
    with open(p) as f:
        for line in f:
            line = line.strip()
            if line and not line.startswith("#"):
                out.append(line)
    return out


class InitProcessor(BasicProcessor):
    step = ModelStep.INIT
    require_columns = False

    # Columns whose distinct count / numeric-parse rate crosses these are
    # auto-typed categorical, standing in for the reference's
    # CountAndFrequentItemsWritable + 0.1*count heuristics (core/autotype).
    CATE_FREQ_THRESHOLD = 0.95

    def process(self) -> int:
        mc = self.model_config
        ds = mc.dataSet
        source = DataSource(self._abs(ds.dataPath), ds.dataDelimiter,
                            header_path=self._abs(ds.headerPath),
                            header_delimiter=ds.headerDelimiter)
        header = source.header
        if ds.targetColumnName:
            from ..config.column_config import ns_match
            hits = [h for h in header if ns_match(h, ds.targetColumnName)]
            if not hits:
                raise ValueError(
                    f"target column {ds.targetColumnName!r} not in header "
                    f"({len(header)} columns)")
            if len(hits) > 1:
                raise ValueError(
                    f"target column {ds.targetColumnName!r} is ambiguous: "
                    f"matches {hits} — use the full namespaced name")
        meta = _read_column_file(ds.metaColumnNameFile, self.dir)
        cate = _read_column_file(ds.categoricalColumnNameFile, self.dir)
        configs = build_initial_column_configs(
            header, ds.targetColumnName, meta_cols=meta, categorical_cols=cate,
            weight_col=ds.weightColumnName)
        if not cate:
            self._auto_type(source, configs)
        self.column_configs = configs
        self.backup(self.paths.column_config_path)
        self.save_column_configs()
        log.info("init: %d columns (%d categorical, %d meta)", len(configs),
                 sum(c.is_categorical() for c in configs), len(meta))
        return 0


    def _auto_type(self, source: DataSource, configs: List[ColumnConfig],
                   sample_rows: int = 200_000) -> None:
        """Numeric/categorical inference via streaming sketches — the
        reference's distinct-count MR job (``core/autotype/``): per-column
        HyperLogLog distinct estimate + bounded frequent items, then the
        ``InitModelProcessor.java:185-250`` rules: a 0/1 binary variable is
        numeric, a column whose frequent items all parse as double is
        numeric, everything else flips to categorical."""
        from ..ops.sketches import FrequentItems, HyperLogLog
        seen = 0
        parse_ok = np.zeros(len(configs), np.int64)
        non_empty = np.zeros(len(configs), np.int64)
        hlls = [HyperLogLog() for _ in configs]
        freqs = [FrequentItems() for _ in configs]
        for chunk in source.iter_chunks(chunk_rows=min(sample_rows, 262144)):
            df = chunk.data
            for i, cc in enumerate(configs):
                vals = df[cc.columnName].to_numpy()
                _, valid = parse_numeric(vals)
                s = pd.Series(vals, dtype=str).str.strip()
                ne = (s != "").to_numpy()
                parse_ok[i] += int(valid.sum())
                non_empty[i] += int(ne.sum())
                live = s[ne].to_numpy()
                hlls[i].update(live)
                freqs[i].update(live)
            seen += len(df)
            if seen >= sample_rows:
                break
        if seen == 0:
            return

        def _all_double(items: List[str]) -> bool:
            # covers the reference's isBinaryVariable special case too: a
            # 0/1 column's frequent items all parse, so it stays numeric
            for v in items:
                try:
                    float(v)
                except ValueError:
                    return False
            return bool(items)

        for i, cc in enumerate(configs):
            distinct = hlls[i].estimate()
            cc.columnStats.distinctCount = distinct
            if cc.is_target() or cc.is_meta():
                continue
            if cc.columnType != ColumnType.N or non_empty[i] == 0:
                continue
            items = freqs[i].top()
            rate = parse_ok[i] / max(1, non_empty[i])
            if rate >= self.CATE_FREQ_THRESHOLD and _all_double(items):
                cc.columnType = ColumnType.N
            else:
                cc.columnType = ColumnType.C
            if cc.columnType == ColumnType.C or rate < 1.0:
                cc.sampleValues = sorted(items)[:20]
