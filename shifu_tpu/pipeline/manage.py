"""Model-set version management — reference ``ManageModelProcessor.java``
(git-branch-like save/switch of model-set versions).

``save [name]`` snapshots ModelConfig.json + ColumnConfig.json + models/
into ``.backup/<name>/``; ``switch <name>`` restores a snapshot (saving the
current state to ``.backup/autosave`` first); ``history`` lists versions;
``show`` prints the current version (ModelAction.SHOW); ``delete <name>``
drops a snapshot; ``cp <dst>`` clones the model set's configs into a new
scaffold (the reference's ``shifu cp <src> <dst>``).
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import List, Optional

from .. import ioutil

log = logging.getLogger(__name__)

VERSIONED = ["ModelConfig.json", "ColumnConfig.json", "models"]


def _backup_dir(model_set_dir: str) -> str:
    return os.path.join(os.path.abspath(model_set_dir), ".backup")


def list_versions(model_set_dir: str) -> List[str]:
    bd = _backup_dir(model_set_dir)
    if not os.path.isdir(bd):
        return []
    return sorted(d for d in os.listdir(bd)
                  if os.path.isdir(os.path.join(bd, d)))


def save_version(model_set_dir: str, name: Optional[str] = None) -> int:
    d = os.path.abspath(model_set_dir)
    if not os.path.isfile(os.path.join(d, "ModelConfig.json")):
        log.error("no ModelConfig.json in %s", d)
        return 1
    name = name or time.strftime("v%Y%m%d-%H%M%S")
    dst = os.path.join(_backup_dir(d), name)
    if os.path.isdir(dst):
        shutil.rmtree(dst)
    os.makedirs(dst)
    for item in VERSIONED:
        src = os.path.join(d, item)
        if os.path.isdir(src):
            shutil.copytree(src, os.path.join(dst, item))
        elif os.path.isfile(src):
            shutil.copy2(src, os.path.join(dst, item))
    _note_current(model_set_dir, name)
    log.info("saved model-set version %s", name)
    return 0


def switch_version(model_set_dir: str, name: str) -> int:
    d = os.path.abspath(model_set_dir)
    src = os.path.join(_backup_dir(d), name)
    if not os.path.isdir(src):
        log.error("no saved version %s (have: %s)", name,
                  list_versions(model_set_dir) or "none")
        return 1
    save_version(model_set_dir, "autosave")  # never lose current state
    for item in VERSIONED:
        cur = os.path.join(d, item)
        snap = os.path.join(src, item)
        if os.path.isdir(cur):
            shutil.rmtree(cur)
        elif os.path.isfile(cur):
            os.remove(cur)
        if os.path.isdir(snap):
            shutil.copytree(snap, cur)
        elif os.path.isfile(snap):
            shutil.copy2(snap, cur)
    _note_current(model_set_dir, name)
    log.info("switched to model-set version %s", name)
    return 0


def show_history(model_set_dir: str) -> int:
    versions = list_versions(model_set_dir)
    if not versions:
        log.info("no saved versions")
        return 0
    for v in versions:
        log.info("version: %s", v)
    return 0


def _current_file(model_set_dir: str) -> str:
    return os.path.join(_backup_dir(model_set_dir), "CURRENT")


def _note_current(model_set_dir: str, name: str) -> None:
    os.makedirs(_backup_dir(model_set_dir), exist_ok=True)
    ioutil.atomic_write_text(_current_file(model_set_dir), name + "\n")


def show_current(model_set_dir: str) -> int:
    """Print the working version (reference ``printCurrentWorker``)."""
    cur = "master"
    cf = _current_file(model_set_dir)
    if os.path.isfile(cf):
        cur = open(cf).read().strip() or cur
    log.info("current version: %s (%d saved)", cur,
             len(list_versions(model_set_dir)))
    return 0


def delete_version(model_set_dir: str, name: str) -> int:
    """Drop a saved snapshot (reference ``ModelAction.DELETE``)."""
    src = os.path.join(_backup_dir(model_set_dir), name)
    if not os.path.isdir(src):
        log.error("no saved version %s (have: %s)", name,
                  list_versions(model_set_dir) or "none")
        return 1
    shutil.rmtree(src)
    cf = _current_file(model_set_dir)
    if os.path.isfile(cf) and open(cf).read().strip() == name:
        os.remove(cf)          # `show` must not report a deleted version
    log.info("deleted model-set version %s", name)
    return 0


def copy_model_set(model_set_dir: str, dst: str) -> int:
    """Clone configs (not artifacts) into a fresh model-set scaffold —
    the reference's ``shifu cp``: start a variant experiment from the
    same dataSet/stats/train config."""
    import json
    d = os.path.abspath(model_set_dir)
    if not os.path.isfile(os.path.join(d, "ModelConfig.json")):
        log.error("no ModelConfig.json in %s", d)
        return 1
    dst = os.path.abspath(dst)
    if os.path.exists(dst):
        log.error("%s already exists", dst)
        return 1
    os.makedirs(dst)
    with open(os.path.join(d, "ModelConfig.json")) as f:
        mc = json.load(f)
    if isinstance(mc.get("basic"), dict):
        mc["basic"]["name"] = os.path.basename(dst)
    ioutil.atomic_write_json(os.path.join(dst, "ModelConfig.json"), mc)
    cc = os.path.join(d, "ColumnConfig.json")
    if os.path.isfile(cc):
        shutil.copy2(cc, os.path.join(dst, "ColumnConfig.json"))
    log.info("copied model set %s -> %s", d, dst)
    return 0
