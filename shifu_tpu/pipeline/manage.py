"""Model-set version management — reference ``ManageModelProcessor.java``
(git-branch-like save/switch of model-set versions).

``save [name]`` snapshots ModelConfig.json + ColumnConfig.json + models/
into ``.backup/<name>/``; ``switch <name>`` restores a snapshot (saving the
current state to ``.backup/autosave`` first); ``history`` lists versions.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import List, Optional

log = logging.getLogger(__name__)

VERSIONED = ["ModelConfig.json", "ColumnConfig.json", "models"]


def _backup_dir(model_set_dir: str) -> str:
    return os.path.join(os.path.abspath(model_set_dir), ".backup")


def list_versions(model_set_dir: str) -> List[str]:
    bd = _backup_dir(model_set_dir)
    if not os.path.isdir(bd):
        return []
    return sorted(d for d in os.listdir(bd)
                  if os.path.isdir(os.path.join(bd, d)))


def save_version(model_set_dir: str, name: Optional[str] = None) -> int:
    d = os.path.abspath(model_set_dir)
    if not os.path.isfile(os.path.join(d, "ModelConfig.json")):
        log.error("no ModelConfig.json in %s", d)
        return 1
    name = name or time.strftime("v%Y%m%d-%H%M%S")
    dst = os.path.join(_backup_dir(d), name)
    if os.path.isdir(dst):
        shutil.rmtree(dst)
    os.makedirs(dst)
    for item in VERSIONED:
        src = os.path.join(d, item)
        if os.path.isdir(src):
            shutil.copytree(src, os.path.join(dst, item))
        elif os.path.isfile(src):
            shutil.copy2(src, os.path.join(dst, item))
    log.info("saved model-set version %s", name)
    return 0


def switch_version(model_set_dir: str, name: str) -> int:
    d = os.path.abspath(model_set_dir)
    src = os.path.join(_backup_dir(d), name)
    if not os.path.isdir(src):
        log.error("no saved version %s (have: %s)", name,
                  list_versions(model_set_dir) or "none")
        return 1
    save_version(model_set_dir, "autosave")  # never lose current state
    for item in VERSIONED:
        cur = os.path.join(d, item)
        snap = os.path.join(src, item)
        if os.path.isdir(cur):
            shutil.rmtree(cur)
        elif os.path.isfile(cur):
            os.remove(cur)
        if os.path.isdir(snap):
            shutil.copytree(snap, cur)
        elif os.path.isfile(snap):
            shutil.copy2(snap, cur)
    log.info("switched to model-set version %s", name)
    return 0


def show_history(model_set_dir: str) -> int:
    versions = list_versions(model_set_dir)
    if not versions:
        log.info("no saved versions")
        return 0
    for v in versions:
        log.info("version: %s", v)
    return 0
