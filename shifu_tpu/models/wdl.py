"""Wide-and-deep model — reference ``core/dtrain/wdl/`` (5.7k LoC:
``WideAndDeep.java:50`` layer graph of DenseLayer / EmbedLayer / WideLayer /
BiasLayer) as one jitted forward.

- deep side: per-categorical-column embedding tables (missing bin = one extra
  row) concatenated with the normalized numeric block, through dense layers;
- wide side: per-categorical-column scalar weight per bin (the sparse LR of
  ``WideLayer``) plus a linear term on numerics;
- output: sigmoid(deep + wide + bias), trained with weighted log loss
  (reference wdl worker ``WDLWorker.java:679-712`` fwd/bwd per record — here
  one batched matmul/gather step).

Embedding gathers batch to one ``take`` per column; XLA fuses the concat +
first dense matmul onto the MXU.
"""

from __future__ import annotations

import io
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import ioutil

import jax
import jax.numpy as jnp


@dataclass
class WDLModelSpec:
    numeric_dim: int
    cat_cardinalities: List[int]        # bins incl. the missing bin, per col
    embed_dim: int = 8
    hidden_nodes: List[int] = field(default_factory=lambda: [64, 32])
    activations: List[str] = field(default_factory=lambda: ["relu", "relu"])
    wide_enable: bool = True
    deep_enable: bool = True
    column_nums: Optional[List[int]] = None
    cat_column_nums: Optional[List[int]] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({
            "version": 1, "kind": "wdl", "numeric_dim": self.numeric_dim,
            "cat_cardinalities": self.cat_cardinalities,
            "embed_dim": self.embed_dim, "hidden_nodes": self.hidden_nodes,
            "activations": self.activations, "wide_enable": self.wide_enable,
            "deep_enable": self.deep_enable, "column_nums": self.column_nums,
            "cat_column_nums": self.cat_column_nums, "extra": self.extra})

    @classmethod
    def from_json(cls, s: str) -> "WDLModelSpec":
        d = json.loads(s)
        return cls(numeric_dim=d["numeric_dim"],
                   cat_cardinalities=d["cat_cardinalities"],
                   embed_dim=d.get("embed_dim", 8),
                   hidden_nodes=d.get("hidden_nodes", [64, 32]),
                   activations=d.get("activations", ["relu", "relu"]),
                   wide_enable=d.get("wide_enable", True),
                   deep_enable=d.get("deep_enable", True),
                   column_nums=d.get("column_nums"),
                   cat_column_nums=d.get("cat_column_nums"),
                   extra=d.get("extra", {}))


def init_params(key, spec: WDLModelSpec) -> Dict:
    from .nn import NNModelSpec, init_params as nn_init
    params: Dict[str, Any] = {}
    n_cat = len(spec.cat_cardinalities)
    keys = jax.random.split(key, n_cat + 2)
    if spec.deep_enable:
        # fan-in scaling: the first dense layer sees embed_dim inputs per
        # column, so variance 1/embed_dim keeps its pre-activations O(1)
        # at any embed_dim/hash-bucket count (a fixed 0.05 degrades as
        # embed_dim grows)
        scale = spec.embed_dim ** -0.5
        params["embed"] = [
            jax.random.normal(keys[i], (card, spec.embed_dim)) * scale
            for i, card in enumerate(spec.cat_cardinalities)]
        deep_in = spec.numeric_dim + n_cat * spec.embed_dim
        deep_spec = NNModelSpec(input_dim=deep_in,
                                hidden_nodes=spec.hidden_nodes,
                                activations=spec.activations, output_dim=1,
                                output_activation="linear")
        params["deep"] = nn_init(keys[-2], deep_spec, "he")
    if spec.wide_enable:
        params["wide_cat"] = [jnp.zeros((card,), jnp.float32)
                              for card in spec.cat_cardinalities]
        params["wide_num"] = jnp.zeros((spec.numeric_dim, 1), jnp.float32)
    params["bias"] = jnp.zeros((1,), jnp.float32)
    return params


# one-hot-matmul lowering cap on TOTAL one-hot elements (N * C * max_card
# — a single high-cardinality column inflates the tensor even at small
# batch): worth materializing for training minibatches (embedding grads
# become matmuls instead of TPU-serialized scatters, measured ~26x on the
# bench step), but a full-dataset scoring pass or a 50k-card column would
# blow HBM — those keep the gather.  33.5M elements = 134 MB f32.
_ONEHOT_MAX_ELEMS = 1 << 25


def _cat_onehot(params: Dict, x_cat):
    """[N, C, K] one-hot over per-column-clipped indices (K = max
    cardinality; a column's padding lanes never activate because its
    indices clip below its own cardinality)."""
    tabs = params.get("embed") or params.get("wide_cat")
    cards = jnp.asarray([t.shape[0] for t in tabs])
    idx = jnp.clip(x_cat, 0, cards[None, :] - 1)
    return jax.nn.one_hot(idx, int(max(t.shape[0] for t in tabs)),
                          dtype=jnp.float32)


def forward_logits(params: Dict, spec: WDLModelSpec, x_num, x_cat):
    """x_num [N, numeric_dim] float; x_cat [N, n_cat] int bin indices.

    Embedding/wide lookups lower two ways: small (training) batches build
    the categorical one-hot ONCE and feed MXU einsums — the backward pass
    is then matmuls, not one scatter-add per column (the per-column
    ``table[idx]`` loop's gathers backprop as scatters the TPU
    serializes); large (scoring) batches keep the per-column gather."""
    n = x_num.shape[0] if spec.numeric_dim else x_cat.shape[0]
    tabs = params.get("embed") or params.get("wide_cat")
    # compute dtype follows the weights (the bf16/mixed training ladder
    # casts the whole param tree): activations run narrow, the logit
    # accumulates in f32 so the sigmoid/loss keep f32 range.  f32 params
    # leave the graph unchanged.
    cdt = tabs[0].dtype if tabs else (
        params["deep"][0]["w"].dtype if spec.deep_enable else jnp.float32)
    use_onehot = bool(tabs) and (
        x_cat.shape[0] * x_cat.shape[1]
        * max(t.shape[0] for t in tabs) <= _ONEHOT_MAX_ELEMS)
    if tabs and not use_onehot:
        # gather lowering: do the lookups here, then share the dense half
        # with the sharded paths so classic-vs-sharded scores stay bitwise
        emb = wide_rows = None
        if spec.deep_enable:
            emb = jnp.stack([
                t[jnp.clip(x_cat[:, i], 0, t.shape[0] - 1)]
                for i, t in enumerate(params["embed"])], axis=1)
        if spec.wide_enable:
            wide_rows = jnp.stack([
                v[jnp.clip(x_cat[:, i], 0, v.shape[0] - 1)]
                for i, v in enumerate(params["wide_cat"])], axis=1)
        return forward_logits_gathered(params, spec, x_num, emb, wide_rows)
    if cdt != jnp.float32 and spec.numeric_dim:
        x_num = x_num.astype(cdt)
    oh = _cat_onehot(params, x_cat) if use_onehot else None
    if oh is not None and cdt != jnp.float32:
        # 0/1 one-hot is exact in bf16; keeping it narrow keeps the
        # lookup einsums' operands (and their grads) narrow too
        oh = oh.astype(cdt)
    logit = jnp.zeros((n, 1)) + params["bias"].astype(jnp.float32)
    if spec.deep_enable:
        parts = [x_num] if spec.numeric_dim else []
        if use_onehot:
            k = oh.shape[-1]
            stacked = jnp.stack([
                jnp.pad(t, ((0, k - t.shape[0]), (0, 0)))
                if t.shape[0] != k else t
                for t in params["embed"]])                # [C, K, E]
            # HIGHEST precision: this einsum is a LOOKUP — default/bf16
            # matmul precision would silently round every table value to
            # bf16 per step (the gather it replaces was exact; same trap
            # as the histogram kernel's convert-round-trip fold)
            emb = jnp.einsum("nck,cke->nce", oh, stacked,
                             precision=jax.lax.Precision.HIGHEST)
            parts.append(emb.reshape(n, -1))             # == concat order
        else:
            for i, table in enumerate(params["embed"]):
                idx = jnp.clip(x_cat[:, i], 0, table.shape[0] - 1)
                parts.append(table[idx])
        h = jnp.concatenate(parts, axis=1)
        from .nn import ACTIVATIONS
        acts = [ACTIVATIONS[a.lower()] for a in spec.activations]
        for li, layer in enumerate(params["deep"][:-1]):
            h = acts[li % len(acts)](h @ layer["w"] + layer["b"])
        last = params["deep"][-1]
        logit = logit + h @ last["w"] + last["b"]
    if spec.wide_enable:
        wide = jnp.zeros((n, 1))
        if use_onehot:
            k = oh.shape[-1]
            wstack = jnp.stack([
                jnp.pad(v, (0, k - v.shape[0]))
                if v.shape[0] != k else v
                for v in params["wide_cat"]])             # [C, K]
            wide = wide + jnp.einsum(
                "nck,ck->n", oh, wstack,
                precision=jax.lax.Precision.HIGHEST)[:, None]
        else:
            for i, wvec in enumerate(params["wide_cat"]):
                idx = jnp.clip(x_cat[:, i], 0, wvec.shape[0] - 1)
                wide = wide + wvec[idx][:, None]
        if spec.numeric_dim:
            wide = wide + x_num @ params["wide_num"]
        logit = logit + wide
    return logit


def _ensure_barrier_batching() -> None:
    """``optimization_barrier`` has no vmap rule in this jax — the barrier
    is identity-shaped, so batching is bind-through (installed only when
    missing; newer jax versions ship their own)."""
    try:
        from jax._src.lax.lax import optimization_barrier_p as p
        from jax.interpreters import batching
    except ImportError:                           # pragma: no cover
        return
    if p in batching.primitive_batchers:
        return

    def _batch(args, dims):
        return p.bind(*args), dims

    batching.primitive_batchers[p] = _batch


_ensure_barrier_batching()


@jax.custom_vjp
def _lookup_barrier(ops):
    """Differentiable ``optimization_barrier`` (no autodiff rule upstream):
    identity that XLA may not fuse across, both directions — the backward
    barrier keeps the dense half's cotangents identical across paths before
    they enter the per-path lookup transposes (scatter-add vs all_gather)."""
    return jax.lax.optimization_barrier(ops)


def _lookup_barrier_fwd(ops):
    return jax.lax.optimization_barrier(ops), None


def _lookup_barrier_bwd(_, cts):
    return (jax.lax.optimization_barrier(cts),)


_lookup_barrier.defvjp(_lookup_barrier_fwd, _lookup_barrier_bwd)


def forward_logits_gathered(params: Dict, spec: WDLModelSpec, x_num,
                            emb, wide_rows):
    """The dense half of the gather lowering with the categorical lookups
    already done: ``emb`` [N, C, E] embedding rows, ``wide_rows`` [N, C]
    wide weights (either may be None when that side is off).  The sharded
    trainer and the sharded serving path both feed their psum-scattered /
    psum'd lookups through THIS function, so their arithmetic is the
    replicated gather path's bit for bit.

    The barrier pins that contract: without it XLA fuses the lookup
    (gather here, psum/psum_scatter in the sharded paths) into the dense
    half and reassociates the final logit adds differently per caller —
    a last-ulp drift that breaks bit-parity between the paths."""
    if emb is not None or wide_rows is not None:
        emb, wide_rows = _lookup_barrier((emb, wide_rows))
    if spec.deep_enable and emb is not None:
        n = emb.shape[0]
        cdt = emb.dtype
    elif wide_rows is not None:
        n = wide_rows.shape[0]
        cdt = wide_rows.dtype
    else:
        n = x_num.shape[0]
        cdt = params["deep"][0]["w"].dtype if spec.deep_enable \
            else jnp.float32
    if cdt != jnp.float32 and spec.numeric_dim:
        x_num = x_num.astype(cdt)
    logit = jnp.zeros((n, 1)) + params["bias"].astype(jnp.float32)
    if spec.deep_enable:
        parts = [x_num] if spec.numeric_dim else []
        for i in range(emb.shape[1]):
            parts.append(emb[:, i, :])
        h = jnp.concatenate(parts, axis=1)
        from .nn import ACTIVATIONS
        acts = [ACTIVATIONS[a.lower()] for a in spec.activations]
        for li, layer in enumerate(params["deep"][:-1]):
            h = acts[li % len(acts)](h @ layer["w"] + layer["b"])
        last = params["deep"][-1]
        logit = logit + h @ last["w"] + last["b"]
    if spec.wide_enable:
        wide = jnp.zeros((n, 1))
        for i in range(wide_rows.shape[1]):
            wide = wide + wide_rows[:, i][:, None]
        if spec.numeric_dim:
            wide = wide + x_num @ params["wide_num"]
        logit = logit + wide
    return logit


def forward(params: Dict, spec: WDLModelSpec, x_num, x_cat):
    return jax.nn.sigmoid(forward_logits(params, spec, x_num, x_cat))


# ---------------------------------------------------------- hashed IDs
def hash_plan(spec: WDLModelSpec):
    """(buckets, [(col_pos, key64), ...]) from the spec's hashed-ID plan,
    or None when the spec has no hashed columns.  The plan is recorded in
    ``spec.extra`` at train time so serving replays the identical map."""
    buckets = int(spec.extra.get("hash_buckets", 0) or 0)
    cols = spec.extra.get("hashed_cols") or []
    keys = spec.extra.get("hash_keys") or []
    if buckets <= 0 or not cols:
        return None
    return buckets, [(int(c), int(k)) for c, k in zip(cols, keys)]


def apply_hash_host(spec: WDLModelSpec, x_cat: np.ndarray) -> np.ndarray:
    """Map hashed-ID columns of a host [N, C] bin matrix into bucket
    space (identity when the spec has no hash plan).  NOT idempotent —
    exactly one layer owns the call per path (trainers and
    ``IndependentWDLModel.compute``; ``forward`` consumes bucket ids)."""
    plan = hash_plan(spec)
    if plan is None:
        return x_cat
    from ..ops import hashing
    buckets, cols = plan
    out = np.array(x_cat, np.int32, copy=True)
    for c, key in cols:
        out[:, c] = hashing.hash_bucket_host(x_cat[:, c], key, buckets)
    return out


def apply_hash_device(spec: WDLModelSpec, x_cat):
    """In-graph replay of :func:`apply_hash_host` for the serving path —
    bit-identical bucket ids (splitmix64 over uint32 limbs)."""
    plan = hash_plan(spec)
    if plan is None:
        return x_cat
    from ..ops import hashing
    buckets, cols = plan
    parts = [x_cat[:, i] for i in range(x_cat.shape[1])]
    for c, key in cols:
        parts[c] = hashing.hash_bucket_device(parts[c], key, buckets)
    return jnp.stack(parts, axis=1)


def per_row_bce(p, y):
    """Clipped binary cross-entropy per row: p, y are [N, 1] -> [N].
    The ONE definition of the WDL loss — trainers (in-RAM, streamed, eval
    sums) all call this so the objective cannot drift between paths."""
    return -(y * jnp.log(jnp.clip(p, 1e-7, 1.0))
             + (1 - y) * jnp.log(jnp.clip(1 - p, 1e-7, 1.0))).sum(axis=-1)


def weighted_loss(params, spec: WDLModelSpec, x_num, x_cat, y, w,
                  l2: float = 0.0):
    p = forward(params, spec, x_num, x_cat)
    per = per_row_bce(p, y)
    loss = (per * w).sum() / jnp.maximum(w.sum(), 1e-9)
    if l2:
        reg = sum((layer["w"] ** 2).sum() for layer in params.get("deep", []))
        reg = reg + sum((t ** 2).sum() for t in params.get("embed", []))
        loss = loss + l2 * reg
    return loss


def l2_grads(params: Dict, l2: float) -> Dict:
    """Gradient of weighted_loss's L2 term — deep weights and embedding
    tables ONLY (bias/wide stay unpenalized), so the streamed trainer's
    accumulated-gradient update regularizes exactly what the in-RAM loss
    does."""
    import jax
    g = jax.tree_util.tree_map(jnp.zeros_like, params)
    for i, layer in enumerate(params.get("deep", [])):
        g["deep"][i]["w"] = 2.0 * l2 * layer["w"]
    for i, t in enumerate(params.get("embed", [])):
        g["embed"][i] = 2.0 * l2 * t
    return g


# ------------------------------------------------------------- save/load
def save_model(path: str, spec: WDLModelSpec, params: Dict) -> None:
    arrays = {"__spec__": np.frombuffer(spec.to_json().encode(), np.uint8),
              "bias": np.asarray(params["bias"], np.float32)}
    if spec.deep_enable:
        for i, t in enumerate(params["embed"]):
            arrays[f"emb{i}"] = np.asarray(t, np.float32)
        for i, layer in enumerate(params["deep"]):
            arrays[f"dw{i}"] = np.asarray(layer["w"], np.float32)
            arrays[f"db{i}"] = np.asarray(layer["b"], np.float32)
    if spec.wide_enable:
        for i, t in enumerate(params["wide_cat"]):
            arrays[f"wc{i}"] = np.asarray(t, np.float32)
        arrays["wn"] = np.asarray(params["wide_num"], np.float32)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    ioutil.atomic_write_bytes(path, buf.getvalue())


def load_model(path: str) -> Tuple[WDLModelSpec, Dict]:
    data = np.load(path)
    spec = WDLModelSpec.from_json(bytes(data["__spec__"]).decode())
    params: Dict[str, Any] = {"bias": jnp.asarray(data["bias"])}
    n_cat = len(spec.cat_cardinalities)
    if spec.deep_enable:
        params["embed"] = [jnp.asarray(data[f"emb{i}"]) for i in range(n_cat)]
        params["deep"] = []
        i = 0
        while f"dw{i}" in data:
            params["deep"].append({"w": jnp.asarray(data[f"dw{i}"]),
                                   "b": jnp.asarray(data[f"db{i}"])})
            i += 1
    if spec.wide_enable:
        params["wide_cat"] = [jnp.asarray(data[f"wc{i}"]) for i in range(n_cat)]
        params["wide_num"] = jnp.asarray(data["wn"])
    return spec, params


class IndependentWDLModel:
    """Standalone scorer (reference ``IndependentWDLModel.java``); consumes
    both planes: normalized numerics + categorical bin indices."""

    input_kind = "both"

    def __init__(self, spec: WDLModelSpec, params: Dict):
        self.spec = spec
        self.params = params
        self._fwd = jax.jit(lambda p, xn, xc: forward(p, spec, xn, xc))

    @classmethod
    def load(cls, path: str) -> "IndependentWDLModel":
        return cls(*load_model(path))

    def compute(self, x_num: np.ndarray, x_cat: np.ndarray) -> np.ndarray:
        x_cat = apply_hash_host(self.spec, np.asarray(x_cat, np.int32))
        return np.asarray(self._fwd(self.params,
                                    jnp.asarray(x_num, jnp.float32),
                                    jnp.asarray(x_cat, jnp.int32)))

    def compute_full(self, x: np.ndarray, bins: np.ndarray) -> np.ndarray:
        """Score from the full transform planes: slice out this model's
        numeric feature block and categorical bin columns (indices recorded
        at train time in the spec)."""
        nf = self.spec.extra.get("num_feat_idx", [])
        cf = self.spec.extra.get("cat_col_idx", [])
        x_num = x[:, nf] if nf else np.zeros((len(x), 0), np.float32)
        x_cat = bins[:, cf] if cf else np.zeros((len(x), 0), np.int32)
        return self.compute(x_num, x_cat)
