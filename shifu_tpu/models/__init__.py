"""Model specs — standalone scorers + serialization.

Each saved model file is self-contained (spec json + arrays in one npz blob),
the role of the reference's ``Independent*Model`` + ``Binary*Serializer``
family (``dtrain/nn/IndependentNNModel.java``,
``dt/IndependentTreeModel.java``, ``wdl/IndependentWDLModel.java``).
``load_any`` sniffs the embedded spec kind, so ``Scorer`` needn\'t know
algorithms.
"""

from __future__ import annotations

import json

import numpy as np


def spec_kind(path: str) -> str:
    data = np.load(path)
    return json.loads(bytes(data["__spec__"]).decode()).get("kind", "nn")


def load_any(path: str):
    """Load any saved model file -> object with ``.compute(x) -> [n, out]``."""
    kind = spec_kind(path)
    if kind == "nn":
        from .nn import IndependentNNModel
        return IndependentNNModel.load(path)
    # LR models are saved as degenerate 0-hidden-layer NN specs (kind
    # "nn", extra.algorithm == "LR") — one scorer path, no parallel LR code.
    if kind == "tree":
        from .tree import IndependentTreeModel
        return IndependentTreeModel.load(path)
    if kind == "wdl":
        from .wdl import IndependentWDLModel
        return IndependentWDLModel.load(path)
    if kind == "svm":
        from .svm import IndependentSVMModel
        return IndependentSVMModel.load(path)
    raise ValueError(f"unknown model kind {kind!r} in {path}")
