"""Tree-ensemble model spec (GBT / RF) — reference
``dt/IndependentTreeModel.java`` + ``BinaryDTSerializer``: a saved forest
scores standalone.

Trees live as complete-binary arrays (split_feat / per-bin left_mask /
leaf_value), so scoring is `depth` gathers over the whole batch — no
per-row recursion.  Input is the binned int matrix (the cleaned data plane);
bin boundaries/categories needed to bin raw data travel in ColumnConfig, and
eval's ModelRunner already produces bins for every row.
"""

from __future__ import annotations

import io
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .. import ioutil

import jax.numpy as jnp

from ..ops.tree import TreeArrays, predict_forest_stacked, stack_forest


@dataclass
class TreeModelSpec:
    algorithm: str                      # "GBT" | "RF"
    n_trees: int
    depth: int
    n_bins: int
    loss: str = "squared"               # GBT leaf-to-score link
    learning_rate: float = 0.1          # GBT shrinkage
    init_score: float = 0.0             # GBT prior (f_0)
    column_nums: Optional[List[int]] = None
    feature_names: Optional[List[str]] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({"version": 1, "kind": "tree",
                           "algorithm": self.algorithm, "n_trees": self.n_trees,
                           "depth": self.depth, "n_bins": self.n_bins,
                           "loss": self.loss, "learning_rate": self.learning_rate,
                           "init_score": self.init_score,
                           "column_nums": self.column_nums,
                           "feature_names": self.feature_names,
                           "extra": self.extra})

    @classmethod
    def from_json(cls, s: str) -> "TreeModelSpec":
        d = json.loads(s)
        return cls(algorithm=d["algorithm"], n_trees=d["n_trees"],
                   depth=d["depth"], n_bins=d["n_bins"],
                   loss=d.get("loss", "squared"),
                   learning_rate=d.get("learning_rate", 0.1),
                   init_score=d.get("init_score", 0.0),
                   column_nums=d.get("column_nums"),
                   feature_names=d.get("feature_names"),
                   extra=d.get("extra", {}))


def save_model(path: str, spec: TreeModelSpec, trees: List[TreeArrays]) -> None:
    arrays = {"__spec__": np.frombuffer(spec.to_json().encode(), np.uint8)}
    for i, t in enumerate(trees):
        arrays[f"sf{i}"] = t.split_feat
        arrays[f"lm{i}"] = np.packbits(t.left_mask, axis=1)
        arrays[f"lv{i}"] = t.leaf_value
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    ioutil.atomic_write_bytes(path, buf.getvalue())


def load_model(path: str) -> Tuple[TreeModelSpec, List[TreeArrays]]:
    data = np.load(path)
    spec = TreeModelSpec.from_json(bytes(data["__spec__"]).decode())
    trees = []
    for i in range(spec.n_trees):
        lm = np.unpackbits(data[f"lm{i}"], axis=1)[:, :spec.n_bins].astype(bool)
        trees.append(TreeArrays(split_feat=data[f"sf{i}"], left_mask=lm,
                                leaf_value=data[f"lv{i}"], depth=spec.depth))
    return spec, trees


class IndependentTreeModel:
    """Standalone forest scorer (reference ``IndependentTreeModel.compute``).
    ``input_kind = 'bins'``: consumes the binned int matrix."""

    input_kind = "bins"

    def __init__(self, spec: TreeModelSpec, trees: List[TreeArrays]):
        self.spec = spec
        self.trees = trees
        self._stacked = None                # lazy same-depth stacked arrays
        self._quant = None                  # lazy quantized-layout arrays

    @classmethod
    def load(cls, path: str) -> "IndependentTreeModel":
        return cls(*load_model(path))

    def _quant_arrays(self):
        if self._quant is None:
            from ..ops.tree_quant import stack_forest_quant
            self._quant = stack_forest_quant(self.trees)
        return self._quant

    def _forest_preds(self, bins) -> np.ndarray:
        """[T, N] (or [T, N, K]) raw per-tree predictions.  The quantized
        traversal is the default: bins stay in the uint8 wire dtype end
        to end (the classic path widened every scoring call to int32 —
        4x the bytes of the plane that dominates serving reads), f32
        appears only at the leaf gather; scores are bit-identical to the
        classic traversal on every backend."""
        from ..ops import tree_quant as tq
        if tq.quant_scoring() and tq.bins_fit_uint8(self.spec.n_bins):
            b = jnp.asarray(bins)
            if b.dtype != jnp.uint8:
                b = b.astype(jnp.uint8)
            return np.asarray(tq.predict_forest_quant(
                *self._quant_arrays(), b, self.trees[0].depth))
        if self._stacked is None:
            self._stacked = stack_forest(self.trees)
        return np.asarray(predict_forest_stacked(
            *self._stacked, jnp.asarray(bins, jnp.int32),
            self.trees[0].depth))

    def compute(self, bins: np.ndarray) -> np.ndarray:
        preds = self._forest_preds(bins)
        if self.spec.algorithm == "GBT":
            f = self.spec.init_score + self.spec.learning_rate * preds.sum(axis=0)
            if self.spec.loss == "log":
                out = 1.0 / (1.0 + np.exp(-f))
            else:
                out = np.clip(f, 0.0, 1.0)
            return out[:, None].astype(np.float32)
        # RF: mean leaf across trees — pos-rate [N] binary, class
        # distribution [N, K] multiclass NATIVE
        out = preds.mean(axis=0)
        if out.ndim == 1:
            out = out[:, None]
        return out.astype(np.float32)
