"""Kernel SVM model spec + standalone scorer.

The reference's SVM is Encog/libsvm C-SVC with linear/poly/sigmoid/RBF
kernels, trained LOCAL-only (``core/alg/SVMTrainer.java:80-145``,
``SVMType.SupportVectorClassification``).  The TPU-shaped model keeps the
support vectors and dual coefficients; scoring is one kernel-matrix matmul
against the SVs — libsvm's per-row SV loop becomes an MXU batch.
"""

from __future__ import annotations

import io
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np

from .. import ioutil

import jax
import jax.numpy as jnp


@dataclass
class SVMModelSpec:
    input_dim: int
    kernel: str = "rbf"                 # linear | poly | sigmoid | rbf
    gamma: float = 0.1
    coef0: float = 0.0
    degree: int = 3
    column_nums: Optional[List[int]] = None
    feature_names: Optional[List[str]] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def to_json(self) -> str:
        return json.dumps({
            "version": 1, "kind": "svm", "input_dim": self.input_dim,
            "kernel": self.kernel, "gamma": self.gamma,
            "coef0": self.coef0, "degree": self.degree,
            "column_nums": self.column_nums,
            "feature_names": self.feature_names, "extra": self.extra})

    @classmethod
    def from_json(cls, s: str) -> "SVMModelSpec":
        d = json.loads(s)
        return cls(input_dim=d["input_dim"], kernel=d.get("kernel", "rbf"),
                   gamma=d.get("gamma", 0.1), coef0=d.get("coef0", 0.0),
                   degree=d.get("degree", 3),
                   column_nums=d.get("column_nums"),
                   feature_names=d.get("feature_names"),
                   extra=d.get("extra", {}))


def kernel_matrix(spec: SVMModelSpec, a, b):
    """[n, m] kernel values, libsvm conventions (``svm.h`` kernel_type):
    rbf ``exp(-gamma |a-b|^2)``, poly ``(gamma a.b + coef0)^degree``,
    sigmoid ``tanh(gamma a.b + coef0)``, linear ``a.b``.  One dot_general
    feeds every kernel — the MXU does libsvm's inner loop."""
    dot = a @ b.T
    if spec.kernel == "linear":
        return dot
    if spec.kernel == "poly":
        return (spec.gamma * dot + spec.coef0) ** spec.degree
    if spec.kernel == "sigmoid":
        return jnp.tanh(spec.gamma * dot + spec.coef0)
    sq = ((a * a).sum(1)[:, None] + (b * b).sum(1)[None, :] - 2.0 * dot)
    return jnp.exp(-spec.gamma * jnp.maximum(sq, 0.0))


def save_model(path: str, spec: SVMModelSpec, sv_x: np.ndarray,
               alpha_y: np.ndarray) -> None:
    arrays = {"__spec__": np.frombuffer(spec.to_json().encode(), np.uint8),
              "sv_x": np.asarray(sv_x, np.float32),
              "alpha_y": np.asarray(alpha_y, np.float32)}
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    ioutil.atomic_write_bytes(path, buf.getvalue())


def load_model(path: str):
    data = np.load(path)
    spec = SVMModelSpec.from_json(bytes(data["__spec__"]).decode())
    return spec, data["sv_x"], data["alpha_y"]


class IndependentSVMModel:
    """Standalone kernel-SVM scorer over saved support vectors.  The
    decision value maps through a sigmoid so scores live in [0, 1] like
    every other scorer (AUC/gain ordering is sigmoid-invariant)."""

    input_kind = "norm"

    def __init__(self, spec: SVMModelSpec, sv_x, alpha_y):
        self.spec = spec
        self.sv_x = jnp.asarray(sv_x, jnp.float32)
        self.alpha_y = jnp.asarray(alpha_y, jnp.float32)
        self._fwd = jax.jit(self._decision)

    def _decision(self, x):
        # the +1 term is the regularized bias fold (augmented kernel —
        # see train/svm_trainer.py)
        k = kernel_matrix(self.spec, x, self.sv_x) + 1.0
        return jax.nn.sigmoid(k @ self.alpha_y)[:, None]

    @classmethod
    def load(cls, path: str) -> "IndependentSVMModel":
        return cls(*load_model(path))

    def compute(self, x) -> np.ndarray:
        return np.asarray(self._fwd(jnp.asarray(x, jnp.float32)))
