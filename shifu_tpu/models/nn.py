"""NN model: jitted MLP forward/backprop — the Encog flat-network replacement.

Covers the reference's NN stack (``core/dtrain/nn/``): custom activations
(``nn/Activation*.java`` — leakyrelu/ptanh/relu/swish plus Encog
sigmoid/tanh/linear), losses (``nn/*ErrorCalculation.java`` — log / squared /
absolute), weight init randomizers (Xavier/He/Lecun,
``core/dtrain/random/``), dropout (``BasicDropoutLayer``), and the standalone
scorer role of ``IndependentNNModel.java`` (a saved spec scores with no
trainer dependencies).

Params are a list-of-layers pytree ``[{"w": [in,out], "b": [out]}, ...]`` —
matmul-shaped for the MXU; batched rows hit one fused kernel per layer.
"""

from __future__ import annotations

import io
import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import ioutil

import jax
import jax.numpy as jnp

SPEC_VERSION = 1

# ----------------------------------------------------------- activations
ACTIVATIONS: Dict[str, Callable] = {
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "leakyrelu": lambda x: jnp.where(x >= 0, x, 0.01 * x),
    "ptanh": lambda x: jnp.where(x >= 0, jnp.tanh(x), 0.25 * jnp.tanh(x)),
    "swish": lambda x: x * jax.nn.sigmoid(x),
    "linear": lambda x: x,
    "log": lambda x: jnp.where(x >= 0, jnp.log1p(x), -jnp.log1p(-x)),
    "sin": jnp.sin,
    "softmax": lambda x: jax.nn.softmax(x, axis=-1),
}


def activation(name: str) -> Callable:
    key = (name or "sigmoid").lower()
    if key not in ACTIVATIONS:
        raise ValueError(f"unknown activation {name!r}; one of {sorted(ACTIVATIONS)}")
    return ACTIVATIONS[key]


@dataclass
class NNModelSpec:
    """Network shape + metadata; serialized alongside weights so the saved
    model scores standalone (reference ``IndependentNNModel.java``)."""
    input_dim: int
    hidden_nodes: List[int]
    activations: List[str]
    output_dim: int = 1
    output_activation: str = "sigmoid"
    loss: str = "squared"           # reference default squared error
    column_nums: Optional[List[int]] = None
    feature_names: Optional[List[str]] = None
    extra: Dict[str, Any] = field(default_factory=dict)

    def layer_dims(self) -> List[Tuple[int, int]]:
        dims = [self.input_dim] + list(self.hidden_nodes) + [self.output_dim]
        return list(zip(dims[:-1], dims[1:]))

    def to_json(self) -> str:
        return json.dumps({
            "version": SPEC_VERSION, "kind": "nn",
            "input_dim": self.input_dim, "hidden_nodes": self.hidden_nodes,
            "activations": self.activations, "output_dim": self.output_dim,
            "output_activation": self.output_activation, "loss": self.loss,
            "column_nums": self.column_nums, "feature_names": self.feature_names,
            "extra": self.extra})

    @classmethod
    def from_json(cls, s: str) -> "NNModelSpec":
        d = json.loads(s)
        return cls(input_dim=d["input_dim"], hidden_nodes=d["hidden_nodes"],
                   activations=d["activations"], output_dim=d.get("output_dim", 1),
                   output_activation=d.get("output_activation", "sigmoid"),
                   loss=d.get("loss", "squared"),
                   column_nums=d.get("column_nums"),
                   feature_names=d.get("feature_names"),
                   extra=d.get("extra", {}))


# ------------------------------------------------------------------- init
def init_params(key, spec: NNModelSpec, initializer: str = "xavier") -> List[Dict]:
    """Weight init per reference randomizers (``core/dtrain/random/``:
    Xavier/He/Lecun; default Xavier)."""
    init = (initializer or "xavier").lower()
    params = []
    for fan_in, fan_out in spec.layer_dims():
        key, sub = jax.random.split(key)
        if init in ("he", "herandomizer"):
            scale = np.sqrt(2.0 / fan_in)
            w = jax.random.normal(sub, (fan_in, fan_out)) * scale
        elif init in ("lecun", "lecunrandomizer"):
            scale = np.sqrt(1.0 / fan_in)
            w = jax.random.normal(sub, (fan_in, fan_out)) * scale
        else:  # xavier uniform
            limit = np.sqrt(6.0 / (fan_in + fan_out))
            w = jax.random.uniform(sub, (fan_in, fan_out), minval=-limit, maxval=limit)
        params.append({"w": w.astype(jnp.float32),
                       "b": jnp.zeros((fan_out,), jnp.float32)})
    return params


# ---------------------------------------------------------------- forward
def forward(params: List[Dict], spec: NNModelSpec, x, *,
            dropout_rate: float = 0.0, rng=None):
    """MLP forward.  Hidden dropout (inverted scaling) only when a key is
    given — eval path stays deterministic.

    The compute dtype follows the WEIGHTS: bf16 params (the mixed/bf16
    training ladder) pull the input and every hidden activation down to
    bf16 — matmuls feed the MXU at native rate and activations halve
    their HBM footprint — while the head logits widen back to f32 so the
    output activation and loss keep f32 dynamic range.  f32 params leave
    the graph byte-identical to before."""
    acts = [activation(a) for a in spec.activations]
    cdt = params[0]["w"].dtype if params else jnp.float32
    h = x.astype(cdt) if cdt != jnp.float32 else x
    n_hidden = len(params) - 1
    for i, layer in enumerate(params[:-1]):
        h = acts[i % max(1, len(acts))](h @ layer["w"] + layer["b"])
        # rng gates dropout statically; the RATE may be a tracer (stacked
        # grid trials carry a per-member dropout array)
        if rng is not None and _nonzero(dropout_rate):
            rng, sub = jax.random.split(rng)
            keep_p = 1.0 - dropout_rate
            keep = jax.random.bernoulli(sub, keep_p, h.shape)
            # divide in h's dtype: a strong-typed f32 keep_p (per-member
            # hyper tracer) would silently widen a bf16 ladder back to f32
            h = jnp.where(keep, h / jnp.asarray(keep_p, h.dtype), 0.0)
    out = h @ params[-1]["w"] + params[-1]["b"]
    if out.dtype != jnp.float32:
        out = out.astype(jnp.float32)
    return activation(spec.output_activation)(out)


def _nonzero(v) -> bool:
    """Static gate for optional terms: a concrete 0.0 skips the op entirely;
    a tracer (per-member hyper array under vmap) always includes it."""
    return not (isinstance(v, (int, float)) and float(v) == 0.0)


LOSSES = {
    "squared": lambda p, y: (p - y) ** 2,
    "absolute": lambda p, y: jnp.abs(p - y),
    "log": lambda p, y: -(y * jnp.log(jnp.clip(p, 1e-7, 1.0))
                          + (1 - y) * jnp.log(jnp.clip(1 - p, 1e-7, 1.0))),
    # hinge on a linear head: y in {0,1} maps to targets {-1,+1}; the SVM
    # path (reference ``core/alg/SVMTrainer.java``) is this loss on the
    # 0-hidden-layer net
    "hinge": lambda p, y: jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * p),
}


def per_row_loss(pred, y, spec: NNModelSpec):
    """Per-row loss for any head.  Multi-class (output_dim > 1): y holds the
    class index, loss is softmax cross-entropy — the NATIVE multi-class mode
    (reference ``ModelTrainConf.MultipleClassification.NATIVE``).  Binary /
    regression: the configured elementwise loss."""
    if spec.output_dim > 1:
        oh = jax.nn.one_hot(jnp.asarray(y).reshape(-1).astype(jnp.int32),
                            spec.output_dim, dtype=pred.dtype)
        return -(oh * jnp.log(jnp.clip(pred, 1e-7, 1.0))).sum(axis=-1)
    lfn = LOSSES.get(spec.loss, LOSSES["squared"])
    return lfn(pred, y).sum(axis=-1)


def weighted_loss(params, spec: NNModelSpec, x, y, w, *,
                  l2: float = 0.0, l1: float = 0.0,
                  dropout_rate: float = 0.0, rng=None):
    """Per-batch mean weighted loss + L1/L2 (reference ``Weight.java:201-213``
    applies reg in the update; applying it in the loss is equivalent under
    gradient descent and lets XLA fuse it)."""
    pred = forward(params, spec, x, dropout_rate=dropout_rate, rng=rng)
    per_row = per_row_loss(pred, y, spec)
    denom = jnp.maximum(w.sum(), 1e-9)
    loss = (per_row * w).sum() / denom
    if _nonzero(l2):
        loss = loss + l2 * sum((layer["w"] ** 2).sum() for layer in params)
    if _nonzero(l1):
        loss = loss + l1 * sum(jnp.abs(layer["w"]).sum() for layer in params)
    return loss


# --------------------------------------------------------------- training
def make_train_step(spec: NNModelSpec, params, optimizer: str = "adam",
                    learning_rate: float = 0.1, l2: float = 0.0, l1: float = 0.0,
                    dropout_rate: float = 0.0, **opt_kwargs):
    """Single-model jitted train step: ``(params, opt_state, x, y, w[, rng])
    -> (params, opt_state, loss)``.  Gradient aggregation across a sharded
    batch is XLA's psum — the NNMaster accumulate step
    (``NNMaster.java:240-249``) with no master."""
    from ..train.optimizers import make_optimizer

    opt = make_optimizer(optimizer, learning_rate, **opt_kwargs)
    opt_state = opt.init(params)

    def step(params, opt_state, x, y, w, rng=None):
        loss, grads = jax.value_and_grad(weighted_loss)(
            params, spec, x, y, w, l2=l2, l1=l1,
            dropout_rate=dropout_rate, rng=rng)
        delta, opt_state = opt.update(grads, opt_state, params)
        params = jax.tree_util.tree_map(lambda p, d: p + d, params, delta)
        return params, opt_state, loss

    return jax.jit(step, donate_argnums=(0, 1)), opt_state


# ------------------------------------------------------------- save/load
def save_model(path: str, spec: NNModelSpec, params) -> None:
    """Self-contained .nn file: npz of weight arrays + the spec json.

    Role of ``BinaryNNSerializer.java`` / ``PersistBasicFloatNetwork``; format
    is ours (npz), not Encog's."""
    arrays = {}
    for i, layer in enumerate(params):
        arrays[f"w{i}"] = np.asarray(layer["w"], np.float32)
        arrays[f"b{i}"] = np.asarray(layer["b"], np.float32)
    arrays["__spec__"] = np.frombuffer(spec.to_json().encode(), dtype=np.uint8)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    ioutil.atomic_write_bytes(path, buf.getvalue())


def load_model(path: str) -> Tuple[NNModelSpec, List[Dict]]:
    data = np.load(path)
    spec = NNModelSpec.from_json(bytes(data["__spec__"]).decode())
    params = []
    for i in range(len(spec.layer_dims())):
        params.append({"w": jnp.asarray(data[f"w{i}"]),
                       "b": jnp.asarray(data[f"b{i}"])})
    return spec, params


class IndependentNNModel:
    """Dependency-light scorer over a saved spec (reference
    ``IndependentNNModel.java``: load once, ``compute()`` per batch)."""

    def __init__(self, spec: NNModelSpec, params):
        self.spec = spec
        self.params = params
        self._fwd = jax.jit(lambda p, x: forward(p, spec, x))

    @classmethod
    def load(cls, path: str) -> "IndependentNNModel":
        return cls(*load_model(path))

    def compute(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._fwd(self.params, jnp.asarray(x, jnp.float32)))


def fit_params_into(old_spec: NNModelSpec, old_params, new_spec: NNModelSpec,
                    key, initializer: str = "xavier"):
    """Continuous-training structure fit-in (reference ``NNMaster.java:
    331-362,605-645``): grow a smaller saved net into a larger configured
    one — fresh-init the new shape, then copy each old weight block into
    the top-left corner of the matching layer.  New rows/cols/layers keep
    their fresh init.  Returns None when the old net does not embed (any
    old dim exceeds the new one, or fewer layers configured than saved)."""
    old_dims = old_spec.layer_dims()
    new_dims = new_spec.layer_dims()
    if len(old_dims) > len(new_dims):
        return None
    for (oi, oo), (ni, no) in zip(old_dims, new_dims):
        if oi > ni or oo > no:
            return None
    # the OUTPUT layer must stay last: when layers are added, the old
    # output layer cannot be copied mid-stack meaningfully — only grow
    # same-depth nets or append hidden layers before a fresh output
    params = init_params(key, new_spec, initializer)
    out = []
    for li, layer in enumerate(params):
        if li < len(old_params) and not (
                len(old_dims) < len(new_dims) and li == len(old_params) - 1):
            w = np.asarray(layer["w"]).copy()
            b = np.asarray(layer["b"]).copy()
            ow = np.asarray(old_params[li]["w"])
            ob = np.asarray(old_params[li]["b"])
            w[:ow.shape[0], :ow.shape[1]] = ow
            b[:ob.shape[0]] = ob
            out.append({"w": jnp.asarray(w), "b": jnp.asarray(b)})
        else:
            out.append(layer)
    return out
