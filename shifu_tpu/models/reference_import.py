"""Importers for the reference's serialized model formats.

Two golden formats ship in the reference's example model sets and are the
only executable artifacts of the reference we can run against (there is no
JVM in this image, so reference LOCAL-mode runs are impossible — the trained
model files stand in as the measured baseline):

- Encog EG text networks (``*.nn``) written by Encog 3.0's persistence
  (reference ``PersistBasicFloatNetwork`` / ``core/alg/NNTrainer.java``),
  e.g. ``example/cancer-judgement/ModelStore/ModelSet1/models/model*.nn``.
- Binary tree forests (``*.gbt`` / ``*.rf``) written by
  ``core/dtrain/dt/BinaryDTSerializer.java:60-160`` and read back by
  ``dt/IndependentTreeModel.java:887-1075`` (version >= 3, optionally
  gzipped), e.g. ``example/readablespec/model0.gbt``.

Parsing these gives a true parity oracle: score the bundled eval data with
the reference's own trained weights through our compute stack and record the
AUC in BASELINE.md; suite tests then assert our trainers reach that AUC on
the same data (tests/test_golden_parity.py).

The importers map onto our native structures where shapes allow (Encog MLP
-> ``models.nn.NNModelSpec`` params) and keep a faithful node-walk scorer
where they don't (reference trees split on raw values, our ``TreeArrays``
split on bin indices).
"""

from __future__ import annotations

import gzip
import io
import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from .nn import NNModelSpec

# -------------------------------------------------- reference fixture data

def load_reference_psv(data_path: str, header_path: str,
                       delimiter: str = "|") -> Dict[str, np.ndarray]:
    """Load a reference example data file (``.pig_header`` + part file)
    into per-column string arrays."""
    with open(header_path) as f:
        header = f.read().strip().split(delimiter)
    rows = [ln.rstrip("\n").split(delimiter)
            for ln in open(data_path) if ln.strip()]
    return {name: np.array([r[i] for r in rows])
            for i, name in enumerate(header)}


def zscore_matrix(cols: Dict[str, np.ndarray], column_configs,
                  cutoff: float = 4.0):
    """(z, raw_by_columnNum): zscore-with-cutoff matrix over final-selected
    columns using the reference ColumnConfig's own mean/stdDev (the eval
    normalization ``core/Normalizer.java:124-287`` applies), plus the raw
    per-columnNum values trees consume."""
    selected = [c for c in column_configs if c.finalSelect]
    n = len(next(iter(cols.values())))
    z = np.zeros((n, len(selected)), np.float32)
    raw: Dict[int, np.ndarray] = {}
    for j, cc in enumerate(selected):
        v = np.array([float(x) if x not in ("", "NA") else np.nan
                      for x in cols[cc.columnName]])
        raw[cc.columnNum] = v
        mean, std = cc.columnStats.mean, cc.columnStats.stdDev
        zz = (np.where(np.isfinite(v), v, mean) - mean) / max(std, 1e-12)
        z[:, j] = np.clip(zz, -cutoff, cutoff)
    return z, raw


# --------------------------------------------------------------- Encog EG

_EG_ACTIVATIONS = {
    "ActivationSigmoid": "sigmoid",
    "ActivationTANH": "tanh",
    "ActivationLinear": "linear",
    "ActivationReLU": "relu",
    "ActivationLOG": "log",
    "ActivationSIN": "sin",
    "ActivationElliott": "sigmoid",      # closest; not used by reference models
}


def _parse_eg_sections(text: str) -> Dict[str, List[str]]:
    sections: Dict[str, List[str]] = {}
    current = None
    for raw in text.splitlines():
        line = raw.strip("\r\n")
        if not line:
            continue
        if line.startswith("[") and line.endswith("]"):
            current = line[1:-1]
            sections[current] = []
        elif current is not None:
            sections[current].append(line)
    return sections


def load_encog_nn(path: str) -> Tuple[NNModelSpec, List[Dict]]:
    """Parse an Encog EG text network into our NN params.

    Encog stores layers output-first (``layerCounts[0]`` = output layer) with
    per-layer flat weight blocks at ``weightIndex``; each block is
    ``[feedCounts[L-1], layerCounts[L]]`` row-major, the trailing column being
    the bias neuron's weight (bias output = ``biasActivation[L]``).  We
    transpose into our input-first ``[{"w": [in,out], "b": [out]}, ...]``.
    """
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        text = f.read()
    if not text.startswith("encog,BasicNetwork"):
        raise ValueError(f"{path}: not an Encog EG BasicNetwork file")
    sections = _parse_eg_sections(text)
    kv: Dict[str, str] = {}
    for line in sections.get("BASIC:NETWORK", []):
        if "=" in line:
            k, v = line.split("=", 1)
            kv[k] = v

    def ints(key: str) -> List[int]:
        return [int(t) for t in kv[key].split(",") if t != ""]

    def floats(key: str) -> List[float]:
        return [float(t) for t in kv[key].split(",") if t != ""]

    layer_counts = ints("layerCounts")          # output-first, incl. bias
    feed_counts = ints("layerFeedCounts")       # output-first, excl. bias
    weight_index = ints("weightIndex")
    weights = np.asarray(floats("weights"), np.float64)
    bias_act = floats("biasActivation")
    n_layers = len(layer_counts)

    acts = [ln.strip().strip('"') for ln in sections.get("BASIC:ACTIVATION", [])
            if ln.strip().strip('"')]

    params: List[Dict] = []
    spec_acts: List[str] = []
    # walk input layer (index n-1) down to the output layer (index 0)
    for layer in range(n_layers - 1, 0, -1):
        out_feed = feed_counts[layer - 1]
        in_count = layer_counts[layer]
        in_feed = feed_counts[layer]
        start = weight_index[layer - 1]
        block = weights[start:start + out_feed * in_count]
        block = block.reshape(out_feed, in_count)
        w = block[:, :in_feed].T.astype(np.float32)           # [in, out]
        if in_count > in_feed:                                # bias neuron
            b = (block[:, in_feed] * bias_act[layer]).astype(np.float32)
        else:
            b = np.zeros(out_feed, np.float32)
        params.append({"w": w, "b": b})
        act_name = _EG_ACTIVATIONS.get(acts[layer - 1], "sigmoid") \
            if layer - 1 < len(acts) else "sigmoid"
        spec_acts.append(act_name)

    spec = NNModelSpec(
        input_dim=feed_counts[-1],
        hidden_nodes=[feed_counts[i] for i in range(n_layers - 2, 0, -1)],
        activations=spec_acts[:-1] or ["sigmoid"],
        output_dim=feed_counts[0],
        output_activation=spec_acts[-1],
        extra={"source": "encog-eg"})
    return spec, params


# ----------------------------------------------------- binary tree forest

@dataclass
class RefNode:
    node_id: int
    gain: float
    wgt_cnt: float
    split_column: int = -1
    split_type: int = 1                 # Split.java:63-64 — 1 CONTINUOUS, 2 CATEGORICAL
    threshold: float = 0.0
    cat_is_left: bool = False
    cat_set: Optional[set] = None       # short category indices
    predict: float = 0.0
    is_leaf: bool = True
    left: Optional["RefNode"] = None
    right: Optional["RefNode"] = None


@dataclass
class RefTreeModel:
    """Parsed reference forest + faithful scorer.

    Scoring mirrors ``IndependentTreeModel.computeRegressionScore``
    (``IndependentTreeModel.java:387-443``): per bag, GBT sums
    ``learning_rate_i * predict_i`` and the final score is the bag mean;
    RF computes ``sum(w_i * predict_i) / sum(w_i)`` per bag, then the bag
    mean.  Numeric splits go left when ``value < threshold`` (missing ->
    column mean first, ``predictNode`` line 524); categorical values are
    category indices, with missing/out-of-range mapped to the dedicated
    missing bucket ``index == categoricalSize`` (lines 530-537) which is
    never inside a split's bitset.
    """
    version: int
    algorithm: str                       # "GBT" | "RF"
    loss: str
    is_classification: bool
    is_one_vs_all: bool
    input_count: int
    mean_by_column: Dict[int, float]
    name_by_column: Dict[int, str]
    categories_by_column: Dict[int, List[str]]
    column_mapping: Dict[int, int]       # columnNum -> dense input index
    bags: List[List[RefNode]] = field(default_factory=list)
    bag_weights: List[List[float]] = field(default_factory=list)

    @property
    def trees(self) -> List[RefNode]:
        return [t for bag in self.bags for t in bag]

    @property
    def tree_weights(self) -> List[float]:
        return [w for bag in self.bag_weights for w in bag]

    def _score_node(self, node: RefNode, x: np.ndarray,
                    idx: np.ndarray, out: np.ndarray) -> None:
        if node.is_leaf or node.left is None or node.right is None:
            out[idx] = node.predict
            return
        col = self.column_mapping.get(node.split_column, node.split_column)
        v = x[idx, col]
        if node.split_type != 2:
            go_left = v < node.threshold
        else:
            cat_size = len(self.categories_by_column.get(node.split_column, ()))
            # missing/out-of-range -> missing bucket index == cat_size
            iv = np.where((v < 0) | (v >= cat_size) | ~np.isfinite(v),
                          float(cat_size), v) + 0.1
            cats = node.cat_set or set()
            in_set = np.isin(iv.astype(np.int64), list(cats) or [-1])
            go_left = in_set if node.cat_is_left else ~in_set
        self._score_node(node.left, x, idx[go_left], out)
        self._score_node(node.right, x, idx[~go_left], out)

    def compute(self, x_by_column: Dict[int, np.ndarray]) -> np.ndarray:
        """Score rows given per-columnNum raw value arrays (missing=NaN;
        categorical columns carry category indices)."""
        n = len(next(iter(x_by_column.values())))
        width = max(self.column_mapping.values()) + 1 if self.column_mapping \
            else max(x_by_column) + 1
        x = np.full((n, width), np.nan)
        for col, dense in self.column_mapping.items():
            v = np.asarray(x_by_column.get(col, np.full(n, np.nan)), np.float64)
            if col not in self.categories_by_column:     # numeric: missing->mean
                mean = self.mean_by_column.get(col, 0.0)
                v = np.where(np.isfinite(v), v, mean)
            x[:, dense] = v
        total = np.zeros(n, np.float64)
        idx = np.arange(n)
        for bag, wgts in zip(self.bags, self.bag_weights):
            bag_score = np.zeros(n, np.float64)
            wsum = 0.0
            for tree, w in zip(bag, wgts):
                out = np.empty(n, np.float64)
                self._score_node(tree, x, idx, out)
                bag_score += w * out
                wsum += w
            if self.algorithm != "GBT":
                bag_score /= max(wsum, 1e-12)
            total += bag_score
        return total / max(len(self.bags), 1)


class _JavaDataInput:
    """DataInput reader for the subset BinaryDTSerializer uses."""

    def __init__(self, data: bytes):
        self._b = io.BytesIO(data)

    def _read(self, n: int) -> bytes:
        d = self._b.read(n)
        if len(d) != n:
            raise EOFError("truncated reference model stream")
        return d

    def read_int(self) -> int:
        return struct.unpack(">i", self._read(4))[0]

    def read_short(self) -> int:
        return struct.unpack(">h", self._read(2))[0]

    def read_byte(self) -> int:
        return struct.unpack(">b", self._read(1))[0]

    def read_boolean(self) -> bool:
        return self._read(1) != b"\x00"

    def read_double(self) -> float:
        return struct.unpack(">d", self._read(8))[0]

    def read_float(self) -> float:
        return struct.unpack(">f", self._read(4))[0]

    def read_utf(self) -> str:
        ln = struct.unpack(">H", self._read(2))[0]
        return self._read(ln).decode("utf-8", errors="replace")

    def read_long_utf(self) -> str:
        """Category entry: short marker < 0 means int-length byte string
        (``IndependentTreeModel.readCategory``)."""
        marker = self.read_short()
        if marker < 0:
            ln = self.read_int()
            return self._read(ln).decode("utf-8", errors="replace")
        return self._read(marker).decode("utf-8", errors="replace")


def _read_bitset(d: _JavaDataInput) -> set:
    """``SimpleBitSet.readFields``: int word count then byte words; bit
    ``i%8`` of word ``i/8`` set means category index ``i`` is in the set."""
    n_words = d.read_int()
    out = set()
    for w in range(n_words):
        byte = d.read_byte() & 0xFF
        for bit in range(8):
            if byte & (1 << bit):
                out.add(w * 8 + bit)
    return out


def _read_node(d: _JavaDataInput, version: int) -> RefNode:
    node = RefNode(node_id=d.read_int(), gain=d.read_float(),
                   wgt_cnt=(d.read_double() if version > 2 else d.read_float()))
    if d.read_boolean():                                     # split present
        node.split_column = d.read_int()
        node.split_type = d.read_byte()
        if node.split_type == 2:                             # CATEGORICAL
            node.cat_is_left = d.read_boolean()
            if not d.read_boolean():                         # not null
                node.cat_set = _read_bitset(d)
        else:                                                # CONTINUOUS
            node.threshold = d.read_double()
    is_real_leaf = d.read_boolean()
    node.is_leaf = is_real_leaf
    if is_real_leaf and d.read_boolean():
        node.predict = d.read_double()
        d.read_byte()                                        # classValue
    if d.read_boolean():
        node.left = _read_node(d, version)
    if d.read_boolean():
        node.right = _read_node(d, version)
    return node


def load_reference_tree(path: str) -> RefTreeModel:
    """Parse a ``BinaryDTSerializer`` forest (version >= 3, gzip or plain)."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw[:2] == b"\x1f\x8b":
        raw = gzip.decompress(raw)
    d = _JavaDataInput(raw)
    version = d.read_int()
    if version < 3:
        raise ValueError(f"{path}: reference tree model version {version} "
                         "< 3 is a legacy layout this importer does not read")
    algorithm = d.read_utf()
    loss = d.read_utf()
    is_classification = d.read_boolean()
    is_one_vs_all = d.read_boolean()
    input_count = d.read_int()

    mean_by_column = {}
    for _ in range(d.read_int()):
        col = d.read_int()
        mean_by_column[col] = d.read_double()
    name_by_column = {}
    for _ in range(d.read_int()):
        col = d.read_int()
        name_by_column[col] = d.read_utf()
    categories_by_column: Dict[int, List[str]] = {}
    for _ in range(d.read_int()):
        col = d.read_int()
        categories_by_column[col] = [d.read_long_utf()
                                     for _ in range(d.read_int())]
    column_mapping = {}
    for _ in range(d.read_int()):
        k = d.read_int()
        column_mapping[k] = d.read_int()

    model = RefTreeModel(version=version, algorithm=algorithm.upper(),
                         loss=loss, is_classification=is_classification,
                         is_one_vs_all=is_one_vs_all, input_count=input_count,
                         mean_by_column=mean_by_column,
                         name_by_column=name_by_column,
                         categories_by_column=categories_by_column,
                         column_mapping=column_mapping)

    bags = 1 if version < 4 else d.read_int()
    for _ in range(bags):
        bag_trees: List[RefNode] = []
        bag_wgts: List[float] = []
        for _ in range(d.read_int()):
            tree_id = d.read_int()                   # noqa: F841
            node_num = d.read_int()                  # noqa: F841
            root = _read_node(d, version)
            lr = d.read_double()
            if root.node_id == 1:                    # Node.ROOT_INDEX
                d.read_double()                      # rootWgtCnt
            # trailing per-tree feature list (TreeNode.readFields)
            n_feats = d.read_int()
            for _ in range(n_feats):
                d.read_int()
            bag_trees.append(root)
            bag_wgts.append(lr)
        model.bags.append(bag_trees)
        model.bag_weights.append(bag_wgts)
    return model


# -------------------------------------------------- WDL binary (.wdl)

def _read_java_string(d: _JavaDataInput) -> Optional[str]:
    """``dtrain/StringUtils.readString``: int byte-length + raw UTF-8
    (0 = null) — NOT readUTF."""
    n = d.read_int()
    if n == 0:
        return None
    return d._read(n).decode("utf-8", errors="replace")


def _read_double_list(d: _JavaDataInput) -> List[float]:
    return [d.read_double() for _ in range(d.read_int())]


def _read_floats(d: _JavaDataInput, shape) -> np.ndarray:
    """Bulk big-endian f32 block (one buffer read, not per-element
    struct calls — WDL weight blocks run to millions of floats)."""
    n = int(np.prod(shape))
    return np.frombuffer(d._read(4 * n), ">f4").reshape(shape) \
        .astype(np.float32)


def _read_wdl_dense(d: _JavaDataInput):
    """``wdl/DenseLayer.readFields`` (WEIGHTS/MODEL_SPEC): l2reg, in, out,
    presence-flagged weights [in][out] + bias [out]."""
    d.read_float()                                   # l2reg
    n_in, n_out = d.read_int(), d.read_int()
    w = _read_floats(d, (n_in, n_out)) if d.read_boolean() \
        else np.zeros((n_in, n_out), np.float32)
    b = _read_floats(d, (n_out,)) if d.read_boolean() \
        else np.zeros(n_out, np.float32)
    return w, b


def _expect(cond: bool, path: str, what: str) -> None:
    """Explicit stream-shape check: ``assert`` would be stripped under
    ``python -O`` while its read side effects must still happen."""
    if not cond:
        raise ValueError(f"{path}: malformed WDL stream — {what}")


def load_reference_wdl(path: str):
    """Parse a ``BinaryWDLSerializer`` stream
    (``core/dtrain/wdl/BinaryWDLSerializer.java:66-125`` writer,
    ``IndependentWDLModel.loadFromStream:198-300`` reader) back into our
    ``(WDLModelSpec, params, column_stats)`` — the round-trip oracle for
    ``export/reference_spec.write_reference_wdl``.  The reference scoring
    composes ``sigmoid(wideLayer + finalLayer(deep))`` exactly like our
    ``models.wdl.forward`` (``WideAndDeep.java:163-199``)."""
    from .wdl import WDLModelSpec

    with open(path, "rb") as f:
        raw = f.read()
    if raw[:2] == b"\x1f\x8b":
        raw = gzip.decompress(raw)
    d = _JavaDataInput(raw)
    version = d.read_int()
    if version != 1:
        raise ValueError(f"{path}: WDL format version {version} != 1")
    d.read_float(); d.read_float(); d.read_double(); d.read_utf()
    norm_type = _read_java_string(d)
    col_stats: Dict[int, dict] = {}
    for _ in range(d.read_int()):                    # NNColumnStats
        num = d.read_int()
        name = _read_java_string(d)
        ctype = d.read_byte()
        cs = {"name": name, "type": ctype, "cutoff": d.read_double(),
              "mean": d.read_double(), "stddev": d.read_double(),
              "woe_mean": d.read_double(), "woe_stddev": d.read_double(),
              "woe_wgt_mean": d.read_double(),
              "woe_wgt_stddev": d.read_double(),
              "boundaries": _read_double_list(d)}
        cs["categories"] = [_read_java_string(d)
                            for _ in range(d.read_int())]
        cs["pos_rates"] = _read_double_list(d)
        cs["count_woes"] = _read_double_list(d)
        cs["weight_woes"] = _read_double_list(d)
        col_stats[num] = cs

    # ---- WideAndDeep.readFields (MODEL_SPEC)
    st = d.read_int()
    if st != 2:
        raise ValueError(f"{path}: serializationType {st} != MODEL_SPEC")
    _expect(d.read_boolean(), path, "null DenseInputLayer")
    numeric_dim = d.read_int()
    hidden = [_read_wdl_dense(d) for _ in range(d.read_int())]
    _expect(d.read_boolean(), path, "null finalLayer")
    final = _read_wdl_dense(d)
    _expect(d.read_boolean(), path, "null EmbedLayer")
    embed, embed_ids = [], []
    for _ in range(d.read_int()):
        cid, n_in, n_out = d.read_int(), d.read_int(), d.read_int()
        tab = _read_floats(d, (n_in, n_out)) if d.read_boolean() \
            else np.zeros((n_in, n_out), np.float32)
        embed.append(tab)
        embed_ids.append(cid)
    _expect(d.read_boolean(), path, "null WideLayer")
    wide_cat, wide_ids = [], []
    for _ in range(d.read_int()):                    # WideFieldLayer
        cid = d.read_int()
        d.read_float()                               # l2reg
        n_in = d.read_int()
        v = _read_floats(d, (n_in,)) if d.read_boolean() \
            else np.zeros(n_in, np.float32)
        wide_cat.append(v)
        wide_ids.append(cid)
    wide_num = np.zeros((numeric_dim, 1), np.float32)
    if d.read_boolean():                             # wide dense part
        wide_num, _ = _read_wdl_dense(d)
    bias = np.zeros(1, np.float32)
    if d.read_boolean():                             # BiasLayer
        bias = np.asarray([d.read_float()], np.float32)
    acts = [d.read_utf() for _ in range(d.read_int())]
    cate_size = {}
    for _ in range(d.read_int()):                    # idBinCateSizeMap
        k = d.read_int()
        cate_size[k] = d.read_int()
    _expect(d.read_int() == numeric_dim, path, "numericalSize mismatch")
    num_ids = [d.read_int() for _ in range(d.read_int())]
    embed_ids2 = [d.read_int() for _ in range(d.read_int())]
    embed_outs = [d.read_int() for _ in range(d.read_int())]
    _wide_ids2 = [d.read_int() for _ in range(d.read_int())]
    hidden_nodes = [d.read_int() for _ in range(d.read_int())]
    d.read_float()                                   # l2reg

    spec = WDLModelSpec(
        numeric_dim=numeric_dim,
        cat_cardinalities=[t.shape[0] for t in embed],
        embed_dim=embed_outs[0] if embed_outs else
        (embed[0].shape[1] if embed else 8),
        hidden_nodes=hidden_nodes or [w.shape[1] for w, _ in hidden],
        activations=acts, column_nums=num_ids or None,
        cat_column_nums=embed_ids2 or embed_ids or None,
        extra={"source": "binary-wdl", "norm_type": norm_type})
    params = {
        "embed": [jnp_asarray_f32(t) for t in embed],
        "deep": [{"w": jnp_asarray_f32(w), "b": jnp_asarray_f32(b)}
                 for w, b in hidden] +
                [{"w": jnp_asarray_f32(final[0]),
                  "b": jnp_asarray_f32(final[1])}],
        "wide_cat": [jnp_asarray_f32(v) for v in wide_cat],
        "wide_num": jnp_asarray_f32(wide_num),
        "bias": jnp_asarray_f32(bias),
    }
    return spec, params, col_stats


def jnp_asarray_f32(a):
    import jax.numpy as jnp
    return jnp.asarray(a, jnp.float32)
