"""Benchmark body: flagship-model training throughput on device.

Baseline derivation (BASELINE.md): the reference publishes no numbers; its
practical NN training configuration is ~1000 Guagua workers × 150MB splits.
Measured LOCAL-mode reference throughput on comparable tabular NN training is
O(10k rows/s/core) in Encog; the driver-set north star is 10× a 100-node YARN
cluster.  We report rows/sec of the jitted data-parallel NN train step and
vs_baseline against a fixed 1e6 rows/s reference point (a 100-worker cluster
at 10k rows/s/worker)."""

from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

BASELINE_ROWS_PER_SEC = 1.0e6  # 100 YARN workers x ~10k rows/s Encog backprop


def run_benchmark(n_rows: int = 1 << 17, n_features: int = 256,
                  hidden: tuple = (512, 256), batch: int = 1 << 14,
                  steps: int = 50) -> Dict[str, Any]:
    import jax
    import jax.numpy as jnp

    from shifu_tpu.models.nn import NNModelSpec, init_params, make_train_step

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n_rows, n_features)), dtype=jnp.float32)
    w = jnp.asarray((rng.normal(size=(n_features,)) / np.sqrt(n_features)), jnp.float32)
    logits = x @ w
    y = jnp.asarray(rng.random(n_rows) < jax.nn.sigmoid(logits), jnp.float32)[:, None]
    wgt = jnp.ones((n_rows, 1), jnp.float32)

    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                      activations=["relu"] * len(hidden), output_dim=1)
    params = init_params(jax.random.PRNGKey(0), spec)
    step_fn, opt_state = make_train_step(spec, params, optimizer="adam",
                                         learning_rate=1e-3)

    n_batches = n_rows // batch
    # warmup/compile
    params, opt_state, loss = step_fn(params, opt_state, x[:batch], y[:batch], wgt[:batch])
    jax.block_until_ready(loss)
    t0 = time.perf_counter()
    done = 0
    for i in range(steps):
        b = (i % n_batches) * batch
        params, opt_state, loss = step_fn(params, opt_state,
                                          x[b:b + batch], y[b:b + batch], wgt[b:b + batch])
        done += batch
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0
    rows_per_sec = done / dt
    return {
        "metric": "nn_train_throughput",
        "value": round(rows_per_sec, 1),
        "unit": "rows/sec",
        "vs_baseline": round(rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
    }
