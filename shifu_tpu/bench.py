"""Benchmark body: flagship-model training throughput on device.

Baseline (measured — see BASELINE.md "Measured baselines" and
tools/measure_baseline.py): the reference's LOCAL trainer is single-threaded
Encog float64 backprop; the same computation measured on this rig
(float64 NumPy backprop, bench shapes 256->512->256->1, batch 4096) runs at
28,850 rows/s/worker.  The driver-set north star is beating a 100-node YARN
cluster 10×, so the cluster-scale baseline is 100 workers × the measured
per-worker rate = 2.885e6 rows/s.  ``vs_baseline`` = device rows/s over that
measured cluster rate.

Also reports GBT training throughput (resident and streamed modes) as extra
keys — same headline JSON line, richer payload.
"""

from __future__ import annotations

import time
from typing import Any, Dict

import numpy as np

from . import ioutil, obs

# the JSONL/metric schema THIS bench emits its per-plane numbers in.
# Hand-maintained on purpose: if obs/ bumps SCHEMA_VERSION without the
# bench being updated (re-validated against the new field layout),
# run_benchmark refuses to run rather than silently emitting records the
# round's BENCH_r0N.json consumers would mis-join with telemetry traces.
# v2: ingest.* counters (spill cache / H2D stall instrumentation).
# v3: varsel_* extras + varsel.* counters (streamed mask-batched
# sensitivity plane: host_syncs / mask_batches / windows / rows_per_sec).
# v4: disk-tail super-batch round — tail_* extras (disk passes / tail
# sweeps / bytes read PER TREE, dual-schedule c2f vs exact rates, RF
# super-batch width) + train.tail_sweeps / tail_repairs counters.
# v5: observability plane v2 — span/event records carry tid (ingest
# track), drift.* gauges, health heartbeats + OpenMetrics snapshots
# derive from the same registry records; bench gains --compare (the
# BENCH_r0N regression differ, which parses exactly these payloads).
# v6: device cost-attribution plane — "cost" records per named
# executable (obs/costs), xla.recompiles / xla.launches +
# ingest.rows_padded counters; bench emits *_mfu / *_achieved_bw extras
# (XLA cost analysis of the timed executable over the device peak
# table) and --compare TRACKS them; --compare with no arguments diffs
# the two newest BENCH_r*.json in the repo root.
# v7: online serving plane — serve.* counters/gauges (requests, batches,
# rows_padded, flush_full/deadline, swaps, bucket_occupancy,
# batch_latency_ms), serve_* extras (sustained QPS + p50/p99 per offered
# load, padding waste, zero-recompile guard); --compare learns the
# LOWER-is-better metric class (*_p50*/*_p99* latency extras regress
# when new > old / threshold).
# v8: request/SLO observability plane — sampled serve.request /
# serve.batch span records (per-request queue/pad/launch/device
# decomposition), slo.* gauges, histogram p50/p99 sketch quantiles; the
# serve bench runs a 1%-sampled traced pass (serve_traced_qps guarded
# at >= 0.95x the QPS floor) and emits latency-decomposition extras
# (serve_queue_frac / serve_device_frac / serve_pad_frac); --compare
# tracks the queue/pad fracs in the lower-is-better class.
# v9: roofline speed round — serve.bucket_occupancy becomes a histogram
# (p50/p99 in metrics.prom), serve.bucket_rungs_added counter, and the
# bench emits nn_train_mixed_* (bf16-ladder training throughput + MFU,
# tracked beside the f32 rows) and serve_quantized_* (uint8-traversal
# AOT scorer throughput + bit-parity flag) extras; --compare picks the
# new *_mfu / *_per_sec / *_qps names up via the existing classes.
# v10: elastic multi-controller plane — dcn.* instruments + the
# quorum_lost monitor field; the bench gains --plane multihost
# (multihost_{1,2,4}p_rows_per_sec scaling curve, tracked by --compare,
# and multihost_recover_s time-to-recover-after-kill, tracked in the
# lower-is-better class via the new *_recover_s suffix).
# v11: model-quality observability plane — scorelog.* / quality.*
# instruments, crash-safe scorelog segments + delayed-label join +
# posttrain.json / quality.json artifacts, the quality heartbeat extra
# and the refresh controller's "quality" trigger source; the bench
# gains --plane quality (serve_scorelog_qps_frac, the on/off saturation
# ratio guarded >= 0.95 and tracked via the new *_qps_frac throughput
# suffix, plus quality_label_flip_detect_s, tracked LOWER-is-better via
# the new *_detect_s suffix).
#
# v12: raw-record serving + fleet — serve_raw_qps_frac (fused-transform
# saturation vs the pre-binned path on the same warmed bucket, guarded
# >= 0.8), and --plane fleet: subprocess replica fleets behind
# serve.router.ServeRouter (serve_fleet_{1,2,4}r_qps aggregate QPS,
# serve_fleet_scaling_frac tracked via the new *_scaling_frac
# throughput suffix, and the replica-SIGKILL drill whose p99 rides the
# lower-is-better latency class while every accepted request completes
# by requeue).
#
# v13: overload protection — --plane overload drives a bounded-queue,
# deadline-propagating server at 1x/2x/4x of its measured saturation
# with an open-loop shed-tolerant client: serve_overload_goodput (the
# 2x headline, tracked via the new *_goodput throughput suffix and
# guarded >= SHIFU_BENCH_OVERLOAD_FLOOR x saturation QPS),
# serve_overload_shed_frac, and serve_overload_p99_ms of ADMITTED
# requests (lower-is-better latency class) — under overload the right
# p99 is the one clients who got answers saw, sheds are coded
# fast-fails counted separately.
#
# v14: one-parse offline pipeline — rawcache.* counters (hits / misses /
# bytes_written) + the ingest.parse_stall_frac gauge; ingest.disk_passes
# now counts RAW STRING-PLANE traversals (cache-served passes never
# touch the reader, so the counter drops when the raw cache engages);
# the bench gains --plane ingest (stats_throughput / norm_throughput:
# pooled parse + raw cache + direct-to-wire norm vs the serial knobs-off
# path in one run, tracked via the existing "throughput" class) and the
# e2e plane emits pipeline_e2e_wall_s (tracked LOWER-is-better via the
# new *_wall_s suffix) + pipeline_e2e_disk_passes (the telemetry-backed
# raw-plane pass count across the whole scripted pipeline).
BENCH_TELEMETRY_SCHEMA = 14

# measured on this rig (tools/measure_baseline.py); provenance in
# BASELINE.md — every headline divides by a MEASURED reference-class
# single-worker rate x the north-star cluster size
MEASURED_CPU_ROWS_PER_SEC = 28850.5          # f64 backprop (2026-07-29)
MEASURED_CPU_TREE_ROWS_TREES_PER_SEC = 43068.1   # np.add.at hist GBT (07-30)
MEASURED_CPU_SCORE_ROWS_PER_SEC = 1505.9     # per-row bagged scorer (07-30)
MEASURED_CPU_STATS_ROWS_PER_SEC = 30872.1    # np.add.at stats pass, 256 cols
                                             # x 4096 buckets (07-31)
MEASURED_CPU_VARSEL_ROWS_COLS_PER_SEC = 510610.6  # f64 per-column frozen-
                                             # forward SE loop, 256-col
                                             # plane x 1x16-tanh net (08-04)
BASELINE_CLUSTER_WORKERS = 100          # north-star cluster size (BASELINE.json)
BASELINE_ROWS_PER_SEC = MEASURED_CPU_ROWS_PER_SEC * BASELINE_CLUSTER_WORKERS
BASELINE_TREE_RATE = (MEASURED_CPU_TREE_ROWS_TREES_PER_SEC
                      * BASELINE_CLUSTER_WORKERS)
BASELINE_SCORE_RATE = (MEASURED_CPU_SCORE_ROWS_PER_SEC
                       * BASELINE_CLUSTER_WORKERS)
BASELINE_STATS_RATE = (MEASURED_CPU_STATS_ROWS_PER_SEC
                       * BASELINE_CLUSTER_WORKERS)
BASELINE_VARSEL_RATE = (MEASURED_CPU_VARSEL_ROWS_COLS_PER_SEC
                        * BASELINE_CLUSTER_WORKERS)


def bench_nn(n_rows: int = 1 << 17, n_features: int = 256,
             hidden: tuple = (512, 256), batch: int = 1 << 12,
             steps: int = 8000, collect: Dict[str, Any] = None) -> float:
    import jax
    import jax.numpy as jnp

    from shifu_tpu.models.nn import NNModelSpec, init_params, make_train_step

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n_rows, n_features)), dtype=jnp.float32)
    w = jnp.asarray((rng.normal(size=(n_features,)) / np.sqrt(n_features)), jnp.float32)
    logits = x @ w
    y = jnp.asarray(rng.random(n_rows) < jax.nn.sigmoid(logits), jnp.float32)[:, None]
    wgt = jnp.ones((n_rows, 1), jnp.float32)

    from functools import partial

    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                       activations=["relu"] * len(hidden), output_dim=1)
    params = init_params(jax.random.PRNGKey(0), spec)
    # bfloat16 matmul inputs with f32 accumulation — the MXU's native rate
    # (the framework's Precision="bfloat16" train param; ~+10% measured on
    # this chip over the backend default)
    with jax.default_matmul_precision("bfloat16"):
        step_fn, opt_state = make_train_step(spec, params, optimizer="adam",
                                             learning_rate=1e-3)
        n_batches = n_rows // batch

        # the whole timing window is ONE executable (lax.scan over steps):
        # per-step dispatch latency over the device link would otherwise
        # dominate the sub-ms step compute
        @partial(jax.jit, static_argnames=("n_steps",), donate_argnums=(0, 1))
        def run_steps(params, opt_state, n_steps: int):
            def body(carry, i):
                p, o = carry
                b = (i % n_batches) * batch
                p, o, loss = step_fn(
                    p, o, jax.lax.dynamic_slice_in_dim(x, b, batch),
                    jax.lax.dynamic_slice_in_dim(y, b, batch),
                    jax.lax.dynamic_slice_in_dim(wgt, b, batch))
                return (p, o), loss
            (p, o), losses = jax.lax.scan(
                body, (params, opt_state),
                jnp.arange(n_steps, dtype=jnp.int32))
            return p, o, losses[-1]

        params, opt_state, loss = run_steps(params, opt_state, steps)
        float(loss)                                  # full warmup sync
        _collect_window_cost(collect, run_steps, (params, opt_state),
                             {"n_steps": steps}, steps * batch)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            params, opt_state, loss = run_steps(params, opt_state, steps)
            float(loss)                              # value-forcing sync
            best = max(best, steps * batch / (time.perf_counter() - t0))
        return best


def bench_nn_mixed(n_rows: int = 1 << 17, n_features: int = 256,
                   hidden: tuple = (512, 256), batch: int = 1 << 12,
                   steps: int = 4000,
                   collect: Dict[str, Any] = None) -> float:
    """NN training throughput under the MIXED-precision ladder
    (``shifu.train.precision=mixed``): bf16 params/activations through
    forward/backward, f32 master copy stepped by the optimizer — the
    bench twin of the trainer path, same scanned-window harness as
    :func:`bench_nn` so ``nn_train_mixed_*`` rows compare directly
    against the f32 ``nn_train_*`` rows."""
    import jax
    import jax.numpy as jnp

    from shifu_tpu.models.nn import NNModelSpec, init_params, weighted_loss
    from shifu_tpu.train.optimizers import (cast_tree, make_optimizer,
                                            mixed_apply, mixed_init)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n_rows, n_features)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(n_features,)) / np.sqrt(n_features),
                    jnp.float32)
    y = jnp.asarray(rng.random(n_rows)
                    < jax.nn.sigmoid(x @ w), jnp.float32)[:, None]
    wgt = jnp.ones((n_rows, 1), jnp.float32)
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                       activations=["relu"] * len(hidden), output_dim=1)
    params = cast_tree(init_params(jax.random.PRNGKey(0), spec),
                       jnp.bfloat16)
    opt = make_optimizer("ADAM", 1e-3)
    state = mixed_init(opt, params)
    n_batches = n_rows // batch

    from functools import partial

    @partial(jax.jit, static_argnames=("n_steps",), donate_argnums=(0, 1))
    def run_steps(params, state, n_steps: int):
        def body(carry, i):
            p, st = carry
            b = (i % n_batches) * batch
            loss, grads = jax.value_and_grad(weighted_loss)(
                p, spec, jax.lax.dynamic_slice_in_dim(x, b, batch),
                jax.lax.dynamic_slice_in_dim(y, b, batch),
                jax.lax.dynamic_slice_in_dim(wgt, b, batch))
            p, st = mixed_apply(opt, grads, st)
            return (p, st), loss
        (p, st), losses = jax.lax.scan(
            body, (params, state), jnp.arange(n_steps, dtype=jnp.int32))
        return p, st, losses[-1]

    params, state, loss = run_steps(params, state, steps)
    float(loss)                                      # full warmup sync
    _collect_window_cost(collect, run_steps, (params, state),
                         {"n_steps": steps}, steps * batch)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        params, state, loss = run_steps(params, state, steps)
        float(loss)                                  # value-forcing sync
        best = max(best, steps * batch / (time.perf_counter() - t0))
    return best


def _collect_window_cost(collect, jitted, args, kwargs, rows: int) -> None:
    """XLA cost analysis of the timed executable (one lowering, no
    second compile): flops / bytes per timing window, for the *_mfu /
    *_achieved_bw extras.  Lowering reads only avals, so donated (dead)
    buffers from the warmup call are fine."""
    if collect is None:
        return
    try:
        ca = jitted.lower(*args, **kwargs).cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            collect["flops_per_window"] = float(ca.get("flops") or 0.0)
            collect["bytes_per_window"] = float(
                ca.get("bytes accessed") or 0.0)
            collect["rows_per_window"] = rows
    except Exception as e:                          # pragma: no cover
        collect["cost_error"] = str(e)[:120]


def _mfu_extras(prefix: str, rows_per_sec: float, col: Dict[str, Any],
                extras: Dict[str, Any]) -> None:
    """Fold a collected window cost into *_mfu / *_achieved_bw extras:
    achieved = window cost / (window rows / best rows-per-sec); MFU =
    achieved FLOP/s over the device peak (obs.costs table,
    SHIFU_TPU_PEAK_FLOPS / SHIFU_TPU_PEAK_BW override)."""
    rows = col.get("rows_per_window")
    if not rows or not rows_per_sec:
        return
    from .obs.costs import resolve_peaks
    peak_f, peak_b, label = resolve_peaks()
    wall = rows / rows_per_sec
    fl, by = col.get("flops_per_window"), col.get("bytes_per_window")
    if fl:
        achieved = fl / wall
        extras[f"{prefix}_achieved_flops"] = round(achieved, 1)
        extras[f"{prefix}_mfu"] = round(achieved / peak_f, 6)
    if by:
        bw = by / wall
        extras[f"{prefix}_achieved_bw"] = round(bw, 1)
        extras[f"{prefix}_bw_frac_of_peak"] = round(bw / peak_b, 6)
    extras.setdefault("peaks_provenance",
                      f"{label}: {peak_f:.3e} FLOP/s, {peak_b:.3e} B/s")


def _bench_forest(train_fn, settings, n_rows: int, n_features: int,
                  n_bins: int) -> float:
    """Shared forest-trainer harness: synthetic rows, compile warmup with
    identical settings, best-of-5 value-synced windows (train_* fetches
    packed trees to host internally, so the window measures real work)."""
    rng = np.random.default_rng(0)
    bins = rng.integers(0, n_bins, size=(n_rows, n_features)).astype(np.int32)
    y = (rng.random(n_rows) < 0.3).astype(np.float32)
    w = np.ones(n_rows, np.float32)
    cat = np.zeros(n_features, bool)
    train_fn(bins, y, w, n_bins, cat, settings)         # compile warmup
    best = 0.0
    for _ in range(5):       # the dev link adds +-20% noise per window;
        t0 = time.perf_counter()                  # best-of-5 tightens it
        res = train_fn(bins, y, w, n_bins, cat, settings)
        dt = time.perf_counter() - t0
        assert res.trees_built == settings.n_trees
        best = max(best, n_rows * settings.n_trees / dt)
    return best


def bench_gbt(n_rows: int = 1 << 17, n_features: int = 64, n_bins: int = 64,
              n_trees: int = 100, depth: int = 6) -> float:
    """GBT training throughput, device-resident rows: rows*trees processed
    per wall-clock second (each tree is a full pass over the rows).
    ``n_trees=100`` = the default model size (``init -model`` GBT TreeNum,
    same as the reference's default) — since r5; was 32, which
    under-amortized the one-time ingest against the per-tree work."""
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt
    return _bench_forest(
        train_gbt,
        DTSettings(n_trees=n_trees, depth=depth, loss="log",
                   learning_rate=0.1),
        n_rows, n_features, n_bins)


def _bench_tree_rows(rng, n_rows: int, n_features: int, n_bins: int,
                     learnable: bool):
    """Synthetic binned rows.  ``learnable=True`` derives y from a sparse
    logit over a few binned columns (fraud-style signal, like the e2e
    plane) instead of pure label noise — the regime real training runs
    in, and the design point of the coarse-to-fine tail: under pure
    noise every split is a coin toss on f32 summation order, so
    resident-prefix speculation diverges adversarially often."""
    bins = rng.integers(0, n_bins, size=(n_rows, n_features)) \
        .astype(np.int16)
    if learnable:
        logit = (0.12 * bins[:, 0] + 0.08 * bins[:, 3]
                 - 0.10 * bins[:, 7] + 0.05 * bins[:, 11]) / n_bins * 8 - 2
        y = (rng.random(n_rows) < 1 / (1 + np.exp(-logit))) \
            .astype(np.float32)
    else:
        y = (rng.random(n_rows) < 0.3).astype(np.float32)
    return bins, y


def bench_gbt_streamed(n_rows: int = 1 << 18, n_features: int = 64,
                       n_bins: int = 64, n_trees: int = 100,
                       depth: int = 5,
                       cache_budget: int = None,
                       learnable: bool = False,
                       reps: int = 5,
                       collect: Dict[str, Any] = None) -> float:
    """GBT throughput in out-of-core streamed mode (windows re-read from the
    stream; measures the full IO+compute path).  ``cache_budget`` caps the
    HBM-resident window cache — pass a budget smaller than the dataset to
    force the disk-tail path (windows past the budget re-stream per level),
    the configuration the 1TB-dataset scenario actually runs.  ``collect``
    (optional dict) receives the ingest accounting of the last timed run:
    disk_passes / tail_sweeps / bytes_read / trees."""
    import json
    import os
    import tempfile

    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt_streamed

    rng = np.random.default_rng(0)
    bins, y = _bench_tree_rows(rng, n_rows, n_features, n_bins, learnable)
    w = np.ones(n_rows, np.float32)
    cat = np.zeros(n_features, bool)
    with tempfile.TemporaryDirectory() as td:
        shard_rows = 8192
        n_shards = 0
        for s in range(0, n_rows, shard_rows):
            e = min(s + shard_rows, n_rows)
            ioutil.atomic_savez(
                os.path.join(td, f"part-{n_shards:05d}.npz"),
                bins=bins[s:e], y=y[s:e], w=w[s:e])
            n_shards += 1
        ioutil.atomic_write_json(
            os.path.join(td, "schema.json"),
            {"columnNums": list(range(n_features)),
             "numShards": n_shards, "numRows": n_rows})
        stream = ShardStream(Shards.open(td), ("bins", "y", "w"),
                             window_rows=16384)
        settings = DTSettings(n_trees=n_trees, depth=depth, loss="log",
                              learning_rate=0.1)
        # compile warmup: identical settings so every executable (fused
        # tree, batched drain) is cached before timing
        train_gbt_streamed(stream, n_bins, cat, settings,
                           cache_budget=cache_budget)
        best = 0.0
        for _ in range(reps):
            t0 = time.perf_counter()
            res = train_gbt_streamed(stream, n_bins, cat, settings,
                                     cache_budget=cache_budget)
            dt = time.perf_counter() - t0
            assert res.trees_built == n_trees
            if cache_budget is not None:
                assert res.disk_passes > 1   # the tail really re-streamed
            best = max(best, n_rows * n_trees / dt)
        if collect is not None:
            collect.update(disk_passes=res.disk_passes,
                           tail_sweeps=res.tail_sweeps,
                           bytes_read=res.bytes_read,
                           trees=res.trees_built)
    return best


def bench_rf(n_rows: int = 1 << 17, n_features: int = 64, n_bins: int = 64,
             n_trees: int = 32, depth: int = 6) -> float:
    """RF training throughput (Poisson bagging + oob validation),
    rows*trees per second — same harness as bench_gbt."""
    from shifu_tpu.train.dt_trainer import DTSettings, train_rf
    return _bench_forest(
        train_rf,
        DTSettings(n_trees=n_trees, depth=depth, impurity="entropy",
                   loss="log", feature_subset="SQRT"),
        n_rows, n_features, n_bins)


def bench_wdl(n_rows: int = 1 << 17, n_num: int = 64, n_cat: int = 32,
              card: int = 64, batch: int = 1 << 12,
              steps: int = 2000, collect: Dict[str, Any] = None) -> float:
    """Wide&deep training-step throughput, same harness shape as
    :func:`bench_nn`: the timing window is ONE scanned executable of
    dual-plane minibatch updates (embedding gathers + wide sparse path +
    deep MLP backprop), value-force synced.  (Reference
    ``core/dtrain/wdl/`` worker backprop; the measured NN-backprop
    baseline is the same reference-class computation and serves as the
    denominator.)"""
    import jax
    import jax.numpy as jnp

    from shifu_tpu.models.wdl import WDLModelSpec, init_params, weighted_loss
    from shifu_tpu.train.optimizers import make_optimizer

    rng = np.random.default_rng(0)
    x_num = jnp.asarray(rng.normal(size=(n_rows, n_num)), jnp.float32)
    x_cat = jnp.asarray(rng.integers(0, card, (n_rows, n_cat)), jnp.int32)
    logit = np.asarray(x_num)[:, 0] * 0.8 \
        + (np.asarray(x_cat)[:, 0] < card // 2) * 0.7 - 0.3
    y = jnp.asarray(rng.random(n_rows) < 1 / (1 + np.exp(-logit)),
                    jnp.float32)
    w = jnp.ones(n_rows, jnp.float32)
    spec = WDLModelSpec(numeric_dim=n_num,
                        cat_cardinalities=[card] * n_cat, embed_dim=16,
                        hidden_nodes=[128, 64],
                        activations=["relu", "relu"])
    params = init_params(jax.random.PRNGKey(0), spec)
    opt = make_optimizer("ADAM", 1e-3)
    opt_state = opt.init(params)
    n_batches = n_rows // batch

    from functools import partial

    with jax.default_matmul_precision("bfloat16"):
        @partial(jax.jit, static_argnames=("n_steps",),
                 donate_argnums=(0, 1))
        def run_steps(params, opt_state, n_steps: int):
            def body(carry, i):
                p, o = carry
                b = (i % n_batches) * batch
                xnb = jax.lax.dynamic_slice_in_dim(x_num, b, batch)
                xcb = jax.lax.dynamic_slice_in_dim(x_cat, b, batch)
                yb = jax.lax.dynamic_slice_in_dim(y, b, batch)
                wb = jax.lax.dynamic_slice_in_dim(w, b, batch)
                loss, grads = jax.value_and_grad(weighted_loss)(
                    p, spec, xnb, xcb, yb[:, None], wb, 0.0)
                delta, o = opt.update(grads, o, p)
                p = jax.tree_util.tree_map(lambda a, d: a + d, p, delta)
                return (p, o), loss
            (p, o), losses = jax.lax.scan(
                body, (params, opt_state),
                jnp.arange(n_steps, dtype=jnp.int32))
            return p, o, losses[-1]

        params, opt_state, loss = run_steps(params, opt_state, steps)
        float(loss)                                  # full warmup sync
        _collect_window_cost(collect, run_steps, (params, opt_state),
                             {"n_steps": steps}, steps * batch)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            params, opt_state, loss = run_steps(params, opt_state, steps)
            float(loss)                              # value-forcing sync
            best = max(best, steps * batch / (time.perf_counter() - t0))
        return best


def bench_wdl_sharded(n_rows: int = 1 << 17, n_num: int = 64,
                      n_cat: int = 32, card: int = 0, batch: int = 1 << 12,
                      steps: int = 2000,
                      collect: Dict[str, Any] = None) -> float:
    """Sharded-table WDL training-step throughput: the same dual-plane
    minibatch updates as :func:`bench_wdl`, but with every embed/wide
    table (and its Adam moments) row-sharded over the data axis and the
    lookups running the sparse per-minibatch gather
    (``train/wdl_shard``).  The timing window is ONE scanned epoch
    executable over pre-batched blocks.

    ``card`` (or ``SHIFU_BENCH_WDL_TABLE_ROWS``) sets the per-table
    cardinality — raise it past single-device HBM to exercise the
    oversized-table scenario sharding exists for; the default matches
    :func:`bench_wdl` so the rows compare the mechanism alone."""
    import jax
    import jax.numpy as jnp
    import os

    from jax.sharding import NamedSharding, PartitionSpec as P

    from shifu_tpu.models.wdl import WDLModelSpec, init_params
    from shifu_tpu.parallel import mesh as meshlib
    from shifu_tpu.train import wdl_shard
    from shifu_tpu.train.optimizers import make_optimizer

    card = card or int(os.environ.get("SHIFU_BENCH_WDL_TABLE_ROWS",
                                      0) or 0) or 64
    if jax.default_backend() == "cpu":
        # host shard_map collectives run ~1000x slower than ICI; a full
        # accelerator-sized window would take tens of minutes on the CI
        # rig for the same steady-state number
        steps = min(steps, 100)
        n_rows = min(n_rows, 1 << 14)
    mesh = meshlib.device_mesh(n_ensemble=1)
    d = mesh.shape["data"]
    batch = max(batch - batch % d, d)
    n_rows = max((n_rows // batch) * batch, batch)
    nb = n_rows // batch

    rng = np.random.default_rng(0)
    x_num = rng.normal(size=(n_rows, n_num)).astype(np.float32)
    x_cat = rng.integers(0, card, (n_rows, n_cat)).astype(np.int32)
    logit = x_num[:, 0] * 0.8 + (x_cat[:, 0] < card // 2) * 0.7 - 0.3
    y = (rng.random(n_rows) < 1 / (1 + np.exp(-logit))).astype(np.float32)
    spec = WDLModelSpec(numeric_dim=n_num,
                        cat_cardinalities=[card] * n_cat, embed_dim=16,
                        hidden_nodes=[128, 64],
                        activations=["relu", "relu"])
    plane = wdl_shard.WDLShardPlane(mesh, spec, 1)
    member = plane.pad_params(init_params(jax.random.PRNGKey(0), spec))
    opt = make_optimizer("ADAM", 1e-3)
    stacked = jax.tree_util.tree_map(lambda a: a[None], member)
    opt_state = jax.tree_util.tree_map(lambda a: a[None], opt.init(member))
    stacked, opt_state = plane.put(stacked, opt_state)
    fns = wdl_shard.build_inram_fns(plane, stacked, opt_state, opt,
                                    "f32", 0.0)

    sh = lambda s: NamedSharding(mesh, s)          # noqa: E731
    xn3 = jax.device_put(x_num.reshape(nb, batch, n_num),
                         sh(P(None, "data", None)))
    xc3 = jax.device_put(x_cat.reshape(nb, batch, n_cat),
                         sh(P(None, "data", None)))
    y3 = jax.device_put(y.reshape(nb, batch), sh(P(None, "data")))
    tw3 = jax.device_put(np.ones((1, nb, batch), np.float32),
                         sh(P("ensemble", None, "data")))
    border = jnp.asarray(np.arange(steps, dtype=np.int32) % nb)

    with jax.default_matmul_precision("bfloat16"):
        epoch = fns["epoch_steps"]
        stacked, opt_state = epoch(stacked, opt_state, xn3, xc3, y3, tw3,
                                   border)
        jax.block_until_ready(stacked)               # full warmup sync
        _collect_window_cost(collect, epoch,
                             (stacked, opt_state, xn3, xc3, y3, tw3,
                              border), {}, steps * batch)
        best = 0.0
        for _ in range(3):
            t0 = time.perf_counter()
            stacked, opt_state = epoch(stacked, opt_state, xn3, xc3, y3,
                                       tw3, border)
            jax.block_until_ready(stacked)           # value-forcing sync
            best = max(best, steps * batch / (time.perf_counter() - t0))
        return best


def bench_eval(n_rows: int = 1 << 20, n_features: int = 256,
               n_models: int = 5) -> float:
    """Eval-stack throughput: a bagged NN scored + confusion-swept (the
    ``EvalScoreUDF`` → ``ConfusionMatrix`` path), rows/sec.

    Device-plane end to end (round 4): the eval matrix is generated in
    HBM (an eval set ingests once; timing the one-time ingest would
    measure the host link), scoring stays in HBM
    (``Scorer.score_device``), and the confusion sweep runs on device
    (``evaluate_scores_device``) — the only transfer per window is the
    packed [5*1024+7]-float curve.  The round-3 harness fetched every
    score and argsorted on host, which capped eval ~2 orders below the
    train plane."""
    import jax
    import jax.numpy as jnp

    from shifu_tpu.eval.metrics import evaluate_scores_device
    from shifu_tpu.eval.scorer import Scorer
    from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                     init_params)

    kx, ky = jax.random.split(jax.random.PRNGKey(0))
    xd = jax.random.normal(kx, (n_rows, n_features), jnp.float32)
    y = (jax.random.uniform(ky, (n_rows,)) < 0.3).astype(jnp.float32)
    wgt = jnp.ones(n_rows, jnp.float32)
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=[512, 256],
                       activations=["relu", "relu"], output_dim=1)
    models = [IndependentNNModel(spec, init_params(jax.random.PRNGKey(i),
                                                   spec))
              for i in range(n_models)]
    scorer = Scorer(models)
    _, mean_d = scorer.score_device(xd)          # compile warmup
    evaluate_scores_device(mean_d, y, wgt)
    best = 0.0
    for _ in range(5):
        t0 = time.perf_counter()
        _, mean_d = scorer.score_device(xd)
        _, result = evaluate_scores_device(mean_d, y, wgt)
        assert np.isfinite(result.areaUnderRoc)  # packed fetch = the sync
        best = max(best, n_rows / (time.perf_counter() - t0))
    return best


def bench_stats(chunk_rows: int = 1 << 18, n_cols: int = 256,
                n_chunks: int = 16, num_buckets: int = 4096) -> float:
    """Stats/ETL-plane throughput: the two-pass per-column sweep (moments +
    fine histogram + missing aggregation with pos/neg channels — the
    ``StatsSpdtI.pig`` + ``UpdateBinningInfo`` MR pair) in rows/sec at 256
    columns, run through the REAL streaming accumulator
    (``ops.binning.NumericAccumulator``): per-chunk kernel outputs
    accumulate on device and drain to host float64 in one packed fetch
    per pass — the round-3 harness fetched per chunk, which billed a full
    ~100 ms link round trip to every 262k rows.  Chunk data is generated
    in HBM (a stats job ingests once; the host link is not the subject);
    the histogram runs the two-level one-hot MXU kernel with packed
    bf16-exact count channels (``ops/hist_pallas``)."""
    import jax
    import jax.numpy as jnp

    from shifu_tpu.ops.binning import NumericAccumulator

    kx, kv, kt = jax.random.split(jax.random.PRNGKey(0), 3)
    x = jax.random.normal(kx, (chunk_rows, n_cols), jnp.float32)
    valid = jax.random.uniform(kv, (chunk_rows, n_cols)) > 0.05
    t = (jax.random.uniform(kt, (chunk_rows,)) < 0.3).astype(jnp.float32)
    w = jnp.ones(chunk_rows, jnp.float32)
    n_rows = chunk_rows * n_chunks

    def sweep() -> None:
        from shifu_tpu.config.model_config import BinningMethod
        acc = NumericAccumulator(n_cols=n_cols, num_buckets=num_buckets,
                                 unit_weight=True)
        for _ in range(n_chunks):                # pass 1, device-pending
            acc.update_moments(x, valid)
        acc.finalize_range()                     # one packed moments drain
        for _ in range(n_chunks):                # pass 2, device-pending
            acc.update_histogram(x, valid, t, w)
        # device-side finalize: boundaries/bin-stats/percentiles in one
        # [C, max_bins]-sized fetch — the fine histogram stays in HBM
        bnds, aggs, _, _ = acc.finalize_sketch(BinningMethod.EqualTotal, 20)
        assert len(bnds) == n_cols and acc.total_rows == n_rows

    sweep()                                      # compile warmup
    best = 0.0
    for _ in range(5):
        t0 = time.perf_counter()
        sweep()                                  # drains force all values
        best = max(best, n_rows / (time.perf_counter() - t0))
    return best


# disk-tail forced: the budget fits ~half the 16384-row windows, the rest
# re-streams per level — the real out-of-core configuration.  Per-window
# accounting since r6: bins ride the compact uint8 wire INTO HBM (1 B/cell
# instead of the old int32's 4), so a prepared GBT window is
# W*(C*1 + 4*4) bytes (bins + y/tw/vw/f f32).
TAIL_BENCH_BUDGET = 2 * 16384 * (64 * 1 + 4 * 4)

# quick-mode throughput floor (rows*trees/s, SHIFU_BENCH_TAIL_FLOOR to
# override): deliberately far below any functioning rig's rate — it
# exists to catch a catastrophic schedule regression (e.g. silent
# fallback to per-(depth x tree) re-streams), not to benchmark the rig
TAIL_BENCH_FLOOR = 5000.0


def bench_gbt_streamed_tail(n_rows: int = 1 << 16, n_trees: int = 4,
                            depth: int = 5) -> Dict[str, Any]:
    """The disk-tail quick mode (`bench.py --plane tail`): small forest,
    budget forces half the windows past the resident cache — the
    out-of-core configuration the super-batched tail schedule exists
    for.  Reports BOTH GBT schedules (coarse-to-fine default vs exact
    per-level sweeps) plus the RF super-batch probe, with per-tree disk
    passes / tail sweeps / bytes read, and enforces the schedule guards:
    c2f tail sweeps per tree bounded (~1 + repairs, >> cheaper than the
    old depth+2), RF passes per tree <= ceil(depth/SB)+1, and a
    conservative throughput floor (SHIFU_BENCH_TAIL_FLOOR)."""
    import os

    from shifu_tpu.train.dt_trainer import _tail_coarse_to_fine

    # both schedules, knob pinned per run, on a learnable fraud-style
    # target — see _bench_tree_rows on why label noise is adversarial
    # for speculation and unrepresentative of training.  The headline is
    # whichever schedule the rig's DEFAULT resolves to (c2f on
    # accelerator backends, exact on CPU — see _tail_coarse_to_fine).
    default_c2f = _tail_coarse_to_fine()
    rates: Dict[str, float] = {}
    stats: Dict[str, Dict[str, Any]] = {}
    prev = os.environ.get("SHIFU_TREE_TAIL_C2F")
    try:
        for tag, knob in (("c2f", "1"), ("exact", "0")):
            os.environ["SHIFU_TREE_TAIL_C2F"] = knob
            col: Dict[str, Any] = {}
            rates[tag] = bench_gbt_streamed(
                n_rows=n_rows, n_trees=n_trees, depth=depth,
                cache_budget=TAIL_BENCH_BUDGET, learnable=True,
                reps=5 if (knob == "1") == default_c2f else 3,
                collect=col)
            stats[tag] = col
    finally:
        if prev is None:
            del os.environ["SHIFU_TREE_TAIL_C2F"]
        else:
            os.environ["SHIFU_TREE_TAIL_C2F"] = prev
    rf = bench_rf_streamed_tail(n_rows=n_rows, depth=depth)

    head = "c2f" if default_c2f else "exact"
    v = rates[head]
    rep = {
        "tail_rows_trees_per_sec": round(v, 1),
        "tail_default_schedule": head,
        "tail_disk_passes_per_tree": round(
            stats[head]["disk_passes"] / stats[head]["trees"], 3),
        "tail_bytes_read_per_tree": int(
            stats[head]["bytes_read"] // stats[head]["trees"]),
        "tail_c2f_rows_trees_per_sec": round(rates["c2f"], 1),
        "tail_c2f_sweeps_per_tree": round(
            stats["c2f"]["tail_sweeps"] / stats["c2f"]["trees"], 3),
        "tail_c2f_bytes_read_per_tree": int(
            stats["c2f"]["bytes_read"] // stats["c2f"]["trees"]),
        "tail_exact_rows_trees_per_sec": round(rates["exact"], 1),
        "tail_exact_sweeps_per_tree": round(
            stats["exact"]["tail_sweeps"] / stats["exact"]["trees"], 3),
        "tail_exact_bytes_read_per_tree": int(
            stats["exact"]["bytes_read"] // stats["exact"]["trees"]),
        "tail_shape": f"{n_rows} rows x {n_trees} trees depth {depth}, "
                      "budget fits ~half the windows (uint8 wire), "
                      "learnable logit target since r9",
    }
    rep.update(rf)
    # schedule guards — the quick mode's job is to fail loudly if the
    # super-batch schedule silently degrades to per-(depth x tree)
    # re-streams (e.g. a knob regression or an always-on repair path)
    floor = float(os.environ.get("SHIFU_BENCH_TAIL_FLOOR",
                                 TAIL_BENCH_FLOOR))
    spt = rep["tail_c2f_sweeps_per_tree"]
    if spt > depth:
        raise AssertionError(
            f"GBT coarse-to-fine tail swept {spt:.2f}x per tree "
            f"(> depth {depth}) — speculation is repairing at the root "
            "near-always; on learnable data the stale-evidence gate "
            "should confirm the upper levels")
    if rep["tail_exact_sweeps_per_tree"] > depth + 2:
        raise AssertionError(
            f"GBT exact tail swept "
            f"{rep['tail_exact_sweeps_per_tree']:.2f}x per tree (> "
            f"depth+2 = {depth + 2}) — the subtraction/leaf-sum "
            "schedule regressed toward per-(depth x tree) re-streams")
    if rep["tail_rf_sweeps_per_tree"] > rep["tail_rf_sweeps_bound"]:
        raise AssertionError(
            f"RF tail swept {rep['tail_rf_sweeps_per_tree']:.2f}x per "
            f"tree > ceil(depth/SB)+1 = {rep['tail_rf_sweeps_bound']} — "
            "the super-batch schedule regressed toward per-tree sweeps")
    if v < floor:
        raise AssertionError(
            f"disk-tail throughput {v:.0f} rows*trees/s below the "
            f"floor {floor:.0f} (SHIFU_BENCH_TAIL_FLOOR)")
    return rep


def bench_rf_streamed_tail(n_rows: int = 1 << 16, n_features: int = 64,
                           n_bins: int = 64, n_trees: int = 16,
                           depth: int = 5) -> Dict[str, Any]:
    """RF disk-tail probe: one super-batch of trees per (depth+2) tail
    sweeps — the acceptance-criterion measurement (passes per tree <=
    ceil(depth/SB)+1) plus throughput."""
    import json
    import math
    import os
    import tempfile

    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream
    from shifu_tpu.train.dt_trainer import (DTSettings, _tail_super_batch,
                                            train_rf_streamed)

    rng = np.random.default_rng(1)
    bins, y = _bench_tree_rows(rng, n_rows, n_features, n_bins,
                               learnable=True)
    w = np.ones(n_rows, np.float32)
    cat = np.zeros(n_features, bool)
    settings = DTSettings(n_trees=n_trees, depth=depth,
                          impurity="entropy", loss="log",
                          feature_subset="SQRT")
    with tempfile.TemporaryDirectory() as td:
        shard_rows = 8192
        n_shards = 0
        for s in range(0, n_rows, shard_rows):
            e = min(s + shard_rows, n_rows)
            ioutil.atomic_savez(
                os.path.join(td, f"part-{n_shards:05d}.npz"),
                bins=bins[s:e], y=y[s:e], w=w[s:e])
            n_shards += 1
        ioutil.atomic_write_json(
            os.path.join(td, "schema.json"),
            {"columnNums": list(range(n_features)),
             "numShards": n_shards, "numRows": n_rows})
        stream = ShardStream(Shards.open(td), ("bins", "y", "w"),
                             window_rows=16384)
        train_rf_streamed(stream, n_bins, cat, settings,
                          cache_budget=TAIL_BENCH_BUDGET)  # warmup
        best, res = 0.0, None
        for _ in range(3):
            t0 = time.perf_counter()
            res = train_rf_streamed(stream, n_bins, cat, settings,
                                    cache_budget=TAIL_BENCH_BUDGET)
            dt = time.perf_counter() - t0
            assert res.trees_built == n_trees
            assert res.disk_passes > 1
            best = max(best, n_rows * n_trees / dt)
    sb = min(n_trees, _tail_super_batch(settings, n_features, n_bins, 2))
    return {
        "tail_rf_rows_trees_per_sec": round(best, 1),
        "tail_rf_super_batch": sb,
        "tail_rf_sweeps_per_tree": round(res.tail_sweeps / n_trees, 3),
        "tail_rf_sweeps_bound": math.ceil(depth / sb) + 1,
        "tail_rf_bytes_read_per_tree": int(res.bytes_read // n_trees),
        "tail_rf_shape": f"{n_rows} rows x {n_trees} trees depth {depth}",
    }


def bench_rf_repeat(n_rows: int = 1 << 17, n_features: int = 64,
                    n_bins: int = 64, n_trees: int = 32, depth: int = 6,
                    repeats: int = 7) -> Dict[str, Any]:
    """RF variance triage (`bench.py --plane rf-repeat`): decompose the
    RF band's run-to-run spread (1.1–2.3x observed across rounds) into

    - COMPILE/CACHE effects: the cold window timed right after
      ``jax.clear_caches()`` (a fresh process's recompile cost — the
      headline harness warms up first, but cross-round drift in compile
      count lands here), vs
    - TUNNEL/RUNTIME noise: min/median/max + CV over ``repeats`` warm
      windows of the identical executable.

    The headline ``bench_rf`` keeps best-of-5; this mode is the
    methodology probe behind the README band (BASELINE.md records the
    decomposition)."""
    import jax

    from shifu_tpu.train.dt_trainer import DTSettings, train_rf

    rng = np.random.default_rng(0)
    bins = rng.integers(0, n_bins, size=(n_rows, n_features)) \
        .astype(np.int32)
    y = (rng.random(n_rows) < 0.3).astype(np.float32)
    w = np.ones(n_rows, np.float32)
    cat = np.zeros(n_features, bool)
    settings = DTSettings(n_trees=n_trees, depth=depth, impurity="entropy",
                          loss="log", feature_subset="SQRT")

    def window() -> float:
        t0 = time.perf_counter()
        res = train_rf(bins, y, w, n_bins, cat, settings)
        assert res.trees_built == n_trees
        return time.perf_counter() - t0

    jax.clear_caches()
    cold_s = window()                      # includes trace + compile
    warm = [window() for _ in range(repeats)]
    rates = sorted(n_rows * n_trees / d for d in warm)
    med_s = sorted(warm)[len(warm) // 2]
    mean_r = float(np.mean(rates))
    return {
        "rf_repeat_shape": f"{n_rows} rows x {n_trees} trees, "
                           f"{repeats} warm windows",
        "rf_repeat_cold_s": round(cold_s, 3),
        "rf_repeat_warm_median_s": round(med_s, 3),
        "rf_repeat_compile_overhead_s": round(cold_s - med_s, 3),
        "rf_repeat_warm_min": round(rates[0], 1),
        "rf_repeat_warm_median": round(rates[len(rates) // 2], 1),
        "rf_repeat_warm_max": round(rates[-1], 1),
        "rf_repeat_warm_cv": round(float(np.std(rates)) / mean_r, 4),
        "rf_repeat_warm_median_vs_baseline": round(
            rates[len(rates) // 2] / BASELINE_TREE_RATE, 3),
        "rf_repeat_warm_band_vs_baseline": [
            round(rates[0] / BASELINE_TREE_RATE, 3),
            round(rates[-1] / BASELINE_TREE_RATE, 3)],
    }


def bench_pipeline_e2e(n_rows: int = None,
                       nn_epochs: int = 10) -> Dict[str, Any]:
    """End-to-end pipeline rehearsal (`bench.py --plane e2e`): scripted
    ``init → stats → norm → train (GBT, TreeNum=100) → train (NN) →
    eval`` over generated fraud-style data
    (``examples/make_fraud_data.py``), per-step wall-clock as
    ``pipeline_e2e_*`` extras.  Unlike the per-plane benches this times
    the REAL pipeline — CSV parse, spill/streamed ingest, validator,
    model serialization — the path a user's ``shifu train`` actually
    takes.  Default ~10M rows (``SHIFU_BENCH_E2E_ROWS`` overrides; CI
    rigs run smaller)."""
    import importlib.util
    import os
    import tempfile

    n_rows = n_rows or int(os.environ.get("SHIFU_BENCH_E2E_ROWS",
                                          10_000_000))
    spec = importlib.util.spec_from_file_location(
        "make_fraud_data",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "examples", "make_fraud_data.py"))
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)

    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.create import InitProcessor, create_new_model
    from shifu_tpu.pipeline.evaluate import EvalProcessor
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor

    out: Dict[str, Any] = {"pipeline_e2e_rows": n_rows}
    # telemetry stays on for the run so ingest.disk_passes (raw string-
    # plane traversals, schema v14) accumulates — the cache/wire win is
    # claimed as a COUNTED pass drop, not a narrative.  Each step's
    # flush snapshots-and-resets the registry, so the total is summed
    # from the per-step metric records in the trace afterwards.
    prev_enabled = obs.enabled()
    obs.set_enabled(True)
    t_all = time.perf_counter()
    with tempfile.TemporaryDirectory() as td:
        t0 = time.perf_counter()
        csv = gen.make(os.path.join(td, "data"), n=n_rows)
        out["pipeline_e2e_datagen_s"] = round(time.perf_counter() - t0, 2)
        mdir = create_new_model("e2e", base_dir=td)
        mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
        mc.dataSet.dataPath = csv
        mc.dataSet.dataDelimiter = "|"
        mc.dataSet.targetColumnName = "tag"
        mc.dataSet.posTags = ["bad"]
        mc.dataSet.negTags = ["good"]
        mc.dataSet.weightColumnName = "weight"
        mc.dataSet.metaColumnNameFile = os.path.join(
            os.path.dirname(csv), "meta.names")
        mc.evals[0].dataSet.dataPath = csv
        mc.evals[0].dataSet.dataDelimiter = "|"
        mc.save(os.path.join(mdir, "ModelConfig.json"))

        def timed(key: str, proc) -> None:
            t0 = time.perf_counter()
            rc = proc.run()
            assert rc == 0, f"{key} failed rc={rc}"
            out[f"pipeline_e2e_{key}_s"] = round(
                time.perf_counter() - t0, 2)

        timed("init", InitProcessor(mdir))
        timed("stats", StatsProcessor(mdir, params={}))
        timed("norm", NormalizeProcessor(mdir, params={}))

        mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
        mc.train.algorithm = Algorithm.GBT
        mc.train.params = {"TreeNum": 100, "MaxDepth": 6, "Loss": "log",
                           "LearningRate": 0.1}
        mc.save(os.path.join(mdir, "ModelConfig.json"))
        timed("train_gbt", TrainProcessor(mdir, params={}))
        timed("eval_gbt", EvalProcessor(mdir, params={}))

        mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
        mc.train.algorithm = Algorithm.NN
        mc.train.params = {"NumHiddenLayers": 2,
                           "NumHiddenNodes": [64, 32],
                           "ActivationFunc": ["relu", "relu"],
                           "LearningRate": 0.001, "Propagation": "ADAM",
                           "Loss": "log"}
        mc.train.numTrainEpochs = nn_epochs
        mc.save(os.path.join(mdir, "ModelConfig.json"))
        timed("train_nn", TrainProcessor(mdir, params={}))
        timed("eval_nn", EvalProcessor(mdir, params={}))

        from shifu_tpu.obs.report import load_blocks, trace_path
        dp = 0.0
        try:
            for block in load_blocks(trace_path(mdir)):
                for m in block["metrics"]:
                    if m.get("name") == "ingest.disk_passes":
                        dp += float(m.get("value") or 0)
        except OSError:
            dp = -1.0                  # no trace — surfaced, not hidden
        out["pipeline_e2e_disk_passes"] = round(dp, 1)
    total = time.perf_counter() - t_all
    out["pipeline_e2e_total_s"] = round(total, 2)
    out["pipeline_e2e_rows_per_sec"] = round(n_rows / total, 1)
    # wall_s duplicates total_s under the *_wall_s suffix --compare
    # tracks LOWER-is-better — the cold end-to-end wall clock IS the
    # one-parse round's headline contract
    out["pipeline_e2e_wall_s"] = round(total, 2)
    obs.set_enabled(True if prev_enabled else None)
    return out


def bench_ingest(n_rows: int = None) -> Dict[str, Any]:
    """One-parse ingest plane (``bench.py --plane ingest``): the scripted
    ``init → stats → norm`` front half over generated fraud-style data,
    run TWICE in one invocation — first with the one-parse machinery
    knobbed OFF (``parseWorkers=0``, ``rawCache=false``,
    ``wireOnly=false``: the serial parse-per-step baseline every round
    before this one ran), then with the defaults (parse pool + columnar
    raw cache + direct-to-wire norm).  Headlines ``stats_throughput`` /
    ``norm_throughput`` are the POOLED raw-rows/sec (tracked by
    ``--compare`` via the throughput class); the serial wall-clocks and
    the speedup ratios ride along informational.  Default ~2M rows
    (``SHIFU_BENCH_INGEST_ROWS`` overrides)."""
    import importlib.util
    import os
    import tempfile

    n_rows = n_rows or int(os.environ.get("SHIFU_BENCH_INGEST_ROWS",
                                          2_000_000))
    spec = importlib.util.spec_from_file_location(
        "make_fraud_data",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "examples", "make_fraud_data.py"))
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)

    from shifu_tpu.config import ModelConfig, environment
    from shifu_tpu.pipeline.create import InitProcessor, create_new_model
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor

    KNOBS = {"shifu.ingest.parseWorkers": "0",
             "shifu.ingest.rawCache": "false",
             "shifu.norm.wireOnly": "false"}
    # knob defaults to restore after the serial leg (set_property has no
    # delete — writing the registry default back is equivalent to unset)
    DEFAULTS = {"shifu.ingest.parseWorkers": "-1",
                "shifu.ingest.rawCache": "true",
                "shifu.norm.wireOnly": "true"}

    out: Dict[str, Any] = {"ingest_rows": n_rows}
    with tempfile.TemporaryDirectory() as td:
        csv = gen.make(os.path.join(td, "data"), n=n_rows)

        def run_leg(name: str, knobs: dict) -> Dict[str, float]:
            prior = {k: environment.get_property(k) for k in knobs}
            for k, v in knobs.items():
                environment.set_property(k, v)
            try:
                mdir = create_new_model(f"ingest_{name}", base_dir=td)
                mc = ModelConfig.load(os.path.join(mdir,
                                                   "ModelConfig.json"))
                mc.dataSet.dataPath = csv
                mc.dataSet.dataDelimiter = "|"
                mc.dataSet.targetColumnName = "tag"
                mc.dataSet.posTags = ["bad"]
                mc.dataSet.negTags = ["good"]
                mc.dataSet.weightColumnName = "weight"
                mc.dataSet.metaColumnNameFile = os.path.join(
                    os.path.dirname(csv), "meta.names")
                mc.save(os.path.join(mdir, "ModelConfig.json"))
                assert InitProcessor(mdir).run() == 0
                t0 = time.perf_counter()
                assert StatsProcessor(mdir, params={}).run() == 0
                stats_s = time.perf_counter() - t0
                t0 = time.perf_counter()
                assert NormalizeProcessor(mdir, params={}).run() == 0
                norm_s = time.perf_counter() - t0
                return {"stats_s": stats_s, "norm_s": norm_s}
            finally:
                for k, v in prior.items():
                    environment.set_property(
                        k, v if v is not None else DEFAULTS[k])

        # untimed warmup leg compiles the stats/norm kernels at the real
        # chunk shapes so the timed serial leg doesn't bill XLA compile
        # to "serial parse" (which would inflate the speedup ratios)
        run_leg("warmup", KNOBS)
        serial = run_leg("serial", KNOBS)
        pooled = run_leg("pooled", DEFAULTS)

    out["ingest_serial_stats_s"] = round(serial["stats_s"], 2)
    out["ingest_serial_norm_s"] = round(serial["norm_s"], 2)
    out["ingest_pooled_stats_s"] = round(pooled["stats_s"], 2)
    out["ingest_pooled_norm_s"] = round(pooled["norm_s"], 2)
    out["stats_throughput"] = round(n_rows / pooled["stats_s"], 1)
    out["norm_throughput"] = round(n_rows / pooled["norm_s"], 1)
    out["ingest_speedup_stats"] = round(
        serial["stats_s"] / pooled["stats_s"], 3)
    out["ingest_speedup_norm"] = round(
        serial["norm_s"] / pooled["norm_s"], 3)
    return out


def bench_resume(n_rows: int = 1 << 16, n_features: int = 64,
                 n_bins: int = 64, n_trees: int = 24,
                 depth: int = 5) -> Dict[str, Any]:
    """Resume-overhead plane (``bench.py --plane resume``): how long until
    the FIRST NEW TREE lands after a restart from a mid-forest checkpoint
    vs a start from scratch.  Three windows:

    - ``cold_first_tree_s``   fresh process state: XLA compile + ingest +
      tree 0 (what a cold `train` pays);
    - ``warm_first_tree_s``   second from-scratch run, executables cached
      (isolates compile from the comparison);
    - ``resume_first_tree_s`` restore 2/3 of the forest and grow the next
      tree — the checkpoint-replay overhead (f rebuilt by replaying the
      committed trees) plus one tree.

    ``resume_overhead_vs_warm`` is the honest headline: the replay cost a
    restarted run pays before producing new work."""
    from shifu_tpu.train.dt_trainer import DTSettings, train_gbt

    rng = np.random.default_rng(0)
    bins = rng.integers(0, n_bins, size=(n_rows, n_features)).astype(np.int32)
    y = (rng.random(n_rows) < 0.3).astype(np.float32)
    w = np.ones(n_rows, np.float32)
    cat = np.zeros(n_features, bool)
    settings = DTSettings(n_trees=n_trees, depth=depth, loss="log",
                          learning_rate=0.1)

    def window(init_trees=None, start_history=None):
        marks = {}
        t0 = time.perf_counter()

        def progress(ti, tr, va):
            marks.setdefault("first", time.perf_counter() - t0)
        res = train_gbt(bins, y, w, n_bins, cat, settings,
                        progress=progress, init_trees=init_trees,
                        start_history=start_history)
        return res, marks["first"], time.perf_counter() - t0

    cold_res, cold_first, cold_total = window()
    _, warm_first, warm_total = window()
    k = (2 * n_trees) // 3                 # the "checkpoint" restore point
    _, resume_first, resume_total = window(
        init_trees=list(cold_res.trees[:k]),
        start_history=list(cold_res.history[:k]))
    return {
        "resume_first_tree_s": round(resume_first, 4),
        "cold_first_tree_s": round(cold_first, 4),
        "warm_first_tree_s": round(warm_first, 4),
        "resume_overhead_vs_warm": round(resume_first - warm_first, 4),
        "resume_total_s": round(resume_total, 4),
        "cold_total_s": round(cold_total, 4),
        "warm_total_s": round(warm_total, 4),
        "restored_trees": k,
        "shape": f"{n_rows} rows x {n_features} feats, {n_trees} trees "
                 f"depth {depth}, restore at {k}",
    }


def bench_varsel(n_rows: int = 1 << 15, n_features: int = 256,
                 n_candidates: int = 128, hidden: int = 16,
                 filter_num: int = 24,
                 mask_batch: int = None) -> Dict[str, Any]:
    """Variable-selection plane (``bench.py --plane varsel``): the
    streamed, mask-batched SE sensitivity job vs the single-worker NumPy
    per-column loop — the reference's ``VarSelectMapper.java:93-120`` MR
    computation, f64 forwards, one frozen column at a time — timed live
    on the same rig AT IDENTICAL SELECTIONS (the top-``filter_num``
    candidate sets must agree, else the speedup is meaningless).

    Rates are rows*candidates/sec (every candidate mask re-scores every
    row, like rows*trees for forests).  The recorded BASELINE.md
    denominator (``MEASURED_CPU_VARSEL_ROWS_COLS_PER_SEC``) comes from
    ``tools/measure_baseline.py`` at the bench NN shapes; the live loop
    here runs the *same* computation at this bench's smaller shape so the
    selections can be compared in seconds."""
    import json
    import os
    import tempfile

    import jax

    from shifu_tpu.data.shards import Shards
    from shifu_tpu.data.streaming import ShardStream, stream_window_rows
    from shifu_tpu.models.nn import NNModelSpec, init_params
    from shifu_tpu.ops import sensitivity as sens
    from shifu_tpu.parallel.mesh import device_mesh

    rng = np.random.default_rng(0)
    x = rng.normal(size=(n_rows, n_features)).astype(np.float32)
    wv = rng.normal(size=n_features) / np.sqrt(n_features)
    y = (rng.random(n_rows) < 1 / (1 + np.exp(-(x @ wv)))) \
        .astype(np.float32)
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=[hidden],
                       activations=["tanh"])
    params = init_params(jax.random.PRNGKey(0), spec)
    masks = sens.mask_matrix(n_features,
                             [[c] for c in range(n_candidates)])

    # ---- single-worker NumPy f64 per-column loop (reference-class)
    w0 = np.asarray(params[0]["w"], np.float64)
    b0 = np.asarray(params[0]["b"], np.float64)
    w1 = np.asarray(params[1]["w"], np.float64)
    b1 = np.asarray(params[1]["b"], np.float64)
    x64 = x.astype(np.float64)
    y64 = y.astype(np.float64)[:, None]

    def np_mse(m):
        h = np.tanh(m @ w0 + b0)
        p = 1.0 / (1.0 + np.exp(-(h @ w1 + b1)))
        return float(((p - y64) ** 2).mean())

    mean_x = x64.mean(axis=0)
    t0 = time.perf_counter()
    base64 = np_mse(x64)
    loop_mse = np.empty(n_candidates)
    for c in range(n_candidates):
        xf = x64.copy()
        xf[:, c] = mean_x[c]
        loop_mse[c] = np_mse(xf)
    loop_dt = time.perf_counter() - t0
    loop_rate = n_rows * n_candidates / loop_dt
    sel_loop = set(np.argsort(-(loop_mse - base64))[:filter_num])

    # ---- streamed mask-batched device job over materialized shards
    with tempfile.TemporaryDirectory() as td:
        shard_rows = 8192
        k = 0
        for s in range(0, n_rows, shard_rows):
            e = min(s + shard_rows, n_rows)
            ioutil.atomic_savez(os.path.join(td, f"part-{k:05d}.npz"),
                                x=x[s:e], y=y[s:e])
            k += 1
        ioutil.atomic_write_json(
            os.path.join(td, "schema.json"),
            {"outputNames": [f"c{i}" for i in range(n_features)],
             "columnNums": list(range(n_features)),
             "numShards": k, "numRows": n_rows})
        shards = Shards.open(td)
        mesh = device_mesh()
        window_rows = stream_window_rows(4 * (n_features + 2),
                                         int(mesh.shape["data"]), shards)

        def run():
            stream = ShardStream(shards, ("x", "y"), window_rows)
            return sens.streamed_sensitivity(stream, spec, params, masks,
                                             mesh=mesh,
                                             mask_batch=mask_batch)

        run()                    # compile warmup + spill-cache build
        best, mse, base = 0.0, None, None
        for _ in range(3):
            t0 = time.perf_counter()
            mse, base, nr = run()
            dt = time.perf_counter() - t0
            assert nr == n_rows
            best = max(best, n_rows * n_candidates / dt)
    sel_stream = set(np.argsort(-(mse - base))[:filter_num])

    return {
        "varsel_stream_rows_cols_per_sec": round(best, 1),
        "varsel_loop_rows_cols_per_sec": round(loop_rate, 1),
        "varsel_speedup_vs_loop": round(best / loop_rate, 2),
        "varsel_selections_match": sel_stream == sel_loop,
        "varsel_shape": f"{n_rows} rows x {n_features} feats, "
                        f"{n_candidates} candidates, top {filter_num}",
    }


# quick-mode catastrophic floor for the serve plane (sustained QPS at the
# top offered load; SHIFU_BENCH_SERVE_FLOOR overrides) — far below any
# functioning rig, exists to catch e.g. a silent per-request-tracing
# regression, not to benchmark the rig
SERVE_BENCH_FLOOR = 5000.0
# low-load p99 must stay bounded by the deadline knob; the slop absorbs
# CI-rig scheduler noise (SHIFU_BENCH_SERVE_P99_SLOP_MS overrides)
SERVE_P99_SLOP_MS = 50.0
# the traced pass head-samples this fraction of requests and must still
# sustain TRACE_OVERHEAD_FLOOR_FRAC x the QPS floor — the acceptance
# bound on per-request tracing overhead at load
TRACE_BENCH_SAMPLE_RATE = 0.01
TRACE_OVERHEAD_FLOOR_FRAC = 0.95


def _trace_decomposition(request_spans) -> Dict[str, float]:
    """Mean latency-decomposition fractions over sampled
    ``serve.request`` span records: where a request's end-to-end time
    went (queue wait / device compute / padding+assembly).  Empty input
    yields no extras."""
    fracs = {"serve_queue_frac": [], "serve_device_frac": [],
             "serve_pad_frac": []}
    for rec in request_spans:
        a = rec.get("attrs") or {}
        e2e = float(a.get("e2e_s") or 0.0)
        if e2e <= 0:
            continue
        fracs["serve_queue_frac"].append(
            float(a.get("queue_wait_s") or 0.0) / e2e)
        fracs["serve_device_frac"].append(
            float(a.get("device_s") or 0.0) / e2e)
        fracs["serve_pad_frac"].append(
            float(a.get("pad_s") or 0.0) / e2e)
    return {k: round(float(np.mean(v)), 4)
            for k, v in fracs.items() if v}


def _serve_open_loop(batcher, pool: np.ndarray, qps: float,
                     duration_s: float):
    """Offered-load open-loop client: arrivals on an ideal schedule in
    ~1 ms bursts (each burst = the single-record requests that landed in
    that tick), stamps = IDEAL arrival times so the latency percentiles
    are free of coordinated omission.  Returns (achieved_qps,
    latencies_s)."""
    clock = batcher.clock
    n_target = int(qps * duration_s)
    period = 1.0 / qps
    pool_n = len(pool)
    tickets, sent = [], 0
    t0 = clock()
    while sent < n_target:
        due = min(n_target, int((clock() - t0) / period) + 1)
        if due <= sent:
            time.sleep(0.0002)
            continue
        idx = np.arange(sent, due)
        tickets.append(batcher.submit_burst(
            pool[idx % pool_n], stamps=t0 + idx * period))
        sent = due
    for t in tickets:
        t.wait(30.0)
    wall = clock() - t0
    lats = np.concatenate([t.latencies() for t in tickets])
    return sent / wall, lats


def _serve_saturation(batcher, pool: np.ndarray, duration_s: float):
    """Top offered load: keep ~4 top-bucket bursts outstanding so the
    device never starves — achieved QPS is the plane's sustained
    capacity.  Returns (achieved_qps, latencies_s)."""
    clock = batcher.clock
    top = batcher._top_bucket()
    pool_n = len(pool)
    tickets, done, sent = [], 0, 0
    t0 = clock()
    while clock() - t0 < duration_s:
        while len(tickets) - done > 4:
            tickets[done].wait(30.0)
            done += 1
        idx = (np.arange(sent, sent + top)) % pool_n
        tickets.append(batcher.submit_burst(pool[idx]))
        sent += top
    for t in tickets[done:]:
        t.wait(30.0)
    wall = clock() - t0
    lats = np.concatenate([t.latencies() for t in tickets])
    return sent / wall, lats


def _serve_closed_loop(batcher, pool: np.ndarray, n_threads: int,
                       duration_s: float):
    """Closed-loop client fleet: N threads each scoring ONE record at a
    time synchronously — the reference's per-row production pattern.
    Returns (achieved_qps, latencies_s)."""
    import threading
    clock = batcher.clock
    lats: list = [[] for _ in range(n_threads)]
    counts = [0] * n_threads

    def worker(i: int) -> None:
        j = i * 97
        end = clock() + duration_s
        while clock() < end:
            t = batcher.submit(pool[j % len(pool)])
            t.wait(10.0)
            lats[i].append(float(t.latencies()[0]))
            counts[i] += 1
            j += 1

    t0 = clock()
    threads = [threading.Thread(target=worker, args=(i,), daemon=True)
               for i in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = clock() - t0
    return sum(counts) / wall, np.asarray(
        [v for ls in lats for v in ls], np.float64)


def bench_serve_quantized(n_rows_grow: int = 1 << 13, n_feat: int = 32,
                          n_bins: int = 64, n_trees: int = 50,
                          depth: int = 6,
                          bucket: int = 512) -> Dict[str, Any]:
    """Quantized-traversal serving micro-bench: a GBT forest behind the
    AOT scorer, scored on uint8 bin batches (``serve_quantized_qps`` =
    ``score_batch`` rows/s at the top bucket), with the bit-parity
    guard the quant path is contracted to: AOT quantized scores must be
    BIT-identical to the classic widened-traversal math."""
    import jax.numpy as jnp

    from shifu_tpu.models.tree import IndependentTreeModel, TreeModelSpec
    from shifu_tpu.ops.tree import (grow_tree, predict_forest_stacked,
                                    stack_forest)
    from shifu_tpu.serve.scorer import AOTScorer

    rng = np.random.default_rng(7)
    gbins = rng.integers(0, n_bins,
                         size=(n_rows_grow, n_feat)).astype(np.int32)
    y = (rng.random(n_rows_grow) < 0.3).astype(np.float32)
    w = np.ones(n_rows_grow, np.float32)
    trees = [grow_tree(gbins, y * (0.8 + 0.4 * rng.random()), w, n_bins,
                       depth) for _ in range(n_trees)]
    spec = TreeModelSpec(algorithm="GBT", n_trees=n_trees, depth=depth,
                         n_bins=n_bins, loss="log", learning_rate=0.1,
                         init_score=-0.5)
    model = IndependentTreeModel(spec, trees)
    scorer = AOTScorer([model], buckets=(bucket,), name="serve.score.quant")
    scorer.warm()
    # the AOT signature covers exactly the features the forest reads
    batch = rng.integers(0, n_bins, size=(bucket, scorer.n_bins_cols)) \
        .astype(np.uint8)
    x = np.zeros((bucket, scorer.n_features), np.float32)
    # classic reference: widened int32 traversal + the same GBT link,
    # in-graph f32 end to end (a host float64 reference would differ in
    # rounding, not in routing)
    import jax

    stacked = stack_forest(trees)
    scale = scorer.scorer.scale

    @jax.jit
    def classic(b):
        preds = predict_forest_stacked(*stacked, b, depth)
        f = spec.init_score + spec.learning_rate * preds.sum(axis=0)
        return (1.0 / (1.0 + jnp.exp(-f))) * scale

    ref = np.asarray(classic(jnp.asarray(batch, jnp.int32)))
    got = scorer.score_batch(x, batch)[:, 0]
    parity = bool(np.array_equal(ref, got))
    best = 0.0
    reps = 5
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(20):
            scorer.score_batch(x, batch)
        best = max(best, 20 * bucket / (time.perf_counter() - t0))
    return {
        "serve_quantized_qps": round(best, 1),
        "serve_quantized_parity": parity,
        "serve_quantized_bins_dtype": str(scorer.bins_dtype),
        "serve_quantized_shape": f"{n_trees} trees depth {depth} x "
                                 f"{n_bins} bins, bucket {bucket}",
    }


def bench_serve(n_features: int = 32, n_models: int = 5,
                hidden: tuple = (64,), low_qps: float = 2000.0,
                mid_qps: float = 20000.0,
                duration_s: float = 0.8) -> Dict[str, Any]:
    """Online-serving plane (``bench.py --plane serve``): the AOT
    device-resident bagged scorer behind the padded-bucket micro-batcher
    (``shifu_tpu/serve/``), driven by closed-loop and open-loop clients
    at several offered loads.

    The reference-class denominator is the measured per-row bagged
    scorer (``MEASURED_CPU_SCORE_ROWS_PER_SEC`` = 1,505.9 rows/s/worker,
    BASELINE.md) — the production surface this plane replaces.  Reports
    sustained QPS, p50/p99 per load, bucket occupancy / padding waste,
    and enforces the plane's two SLO guards: a warmed server performs
    ZERO recompiles across the sweep (the shape-churn sentinel), and
    low-load p99 stays bounded by the ``maxDelayMs`` deadline."""
    import os

    import jax

    from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                     init_params)
    from shifu_tpu.serve import ServeServer, serve_recompile_count

    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                       activations=["relu"] * len(hidden), output_dim=1)
    models = [IndependentNNModel(spec,
                                 init_params(jax.random.PRNGKey(i), spec))
              for i in range(n_models)]
    server = ServeServer(models=models, key="bench").start()
    batcher = server.batcher
    scorer = server.registry.get("bench")
    deadline_ms = batcher.max_delay_s * 1000.0
    rng = np.random.default_rng(0)
    pool = rng.normal(size=(4096, n_features)).astype(np.float32)
    try:
        # warm: every bucket compiled + launched, dispatch paths hot
        for n in (1, 3, *scorer.buckets):
            batcher.score_sync(pool[:n])
        recompiles0 = serve_recompile_count()
        stats0 = dict(batcher.stats)

        # collector pauses land straight in the tail percentiles (20 ms
        # p99 spikes at low load measured on this rig) — standard
        # latency-bench hygiene: no GC inside the measured window
        import gc
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            closed_qps, closed_lats = _serve_closed_loop(
                batcher, pool, n_threads=8, duration_s=duration_s / 2)
            low_ach, low_lats = _serve_open_loop(batcher, pool, low_qps,
                                                 duration_s)
            mid_ach, mid_lats = _serve_open_loop(batcher, pool, mid_qps,
                                                 duration_s)
            max_ach, max_lats = _serve_saturation(batcher, pool,
                                                  duration_s)
        finally:
            if gc_was_enabled:
                gc.enable()
        recompiles = serve_recompile_count() - recompiles0

        # traced pass: head-sample 1% of requests (telemetry on) and
        # re-measure sustained QPS — the per-request-tracing overhead
        # acceptance — then read the sampled serve.request records for
        # the latency-decomposition extras
        prev_enabled = obs.enabled()
        obs.set_enabled(True)
        rec_before = len(obs.pending_records())
        batcher.trace_sample_rate = TRACE_BENCH_SAMPLE_RATE
        try:
            traced_qps, _ = _serve_saturation(batcher, pool,
                                              duration_s / 2)
            # one explicit-id burst so even a tiny sweep yields a
            # decomposition sample (an explicit id forces sampling)
            batcher.submit_burst(pool[:37],
                                 trace_id="bench-decomp").wait(30.0)
        finally:
            batcher.trace_sample_rate = 0.0
            request_spans = [
                r for r in obs.pending_records()[rec_before:]
                if r.get("kind") == "span"
                and r.get("name") == "serve.request"]
            obs.set_enabled(True if prev_enabled else None)
    finally:
        server.stop()

    def pct(lats, q):
        return round(float(np.percentile(lats, q)) * 1000.0, 3)

    rows = batcher.stats["rows"] - stats0["rows"]
    padded = batcher.stats["rows_padded"] - stats0["rows_padded"]
    batches = batcher.stats["batches"] - stats0["batches"]
    rep: Dict[str, Any] = {
        "serve_qps_sustained": round(max_ach, 1),
        "serve_deadline_ms": deadline_ms,
        "serve_low_qps_offered": low_qps,
        "serve_low_qps": round(low_ach, 1),
        "serve_low_p50_ms": pct(low_lats, 50),
        "serve_low_p99_ms": pct(low_lats, 99),
        "serve_mid_qps_offered": mid_qps,
        "serve_mid_qps": round(mid_ach, 1),
        "serve_mid_p50_ms": pct(mid_lats, 50),
        "serve_mid_p99_ms": pct(mid_lats, 99),
        "serve_max_p50_ms": pct(max_lats, 50),
        "serve_max_p99_ms": pct(max_lats, 99),
        "serve_closed_qps": round(closed_qps, 1),
        "serve_closed_p50_ms": pct(closed_lats, 50),
        "serve_closed_p99_ms": pct(closed_lats, 99),
        "serve_recompiles_after_warm": int(recompiles),
        "serve_traced_qps": round(traced_qps, 1),
        "serve_trace_sample_rate": TRACE_BENCH_SAMPLE_RATE,
        "serve_trace_sampled": len(request_spans),
        **_trace_decomposition(request_spans),
        "serve_batches": int(batches),
        "serve_rows_padded": int(padded),
        "serve_padding_waste_frac": round(
            padded / max(rows + padded, 1), 4),
        "serve_bucket_ladder": ",".join(map(str, scorer.buckets)),
        "serve_bucket_counts": ",".join(
            f"{b}:{c}" for b, c in sorted(batcher.bucket_counts.items())),
        "serve_shape": f"{n_models} NN models {n_features}->"
                       f"{list(hidden)}->1 stacked, pool 4096 rows, "
                       f"clients: closed 8-thread / open "
                       f"{low_qps:.0f}+{mid_qps:.0f} QPS / saturation",
    }
    # quantized-traversal serving rows ride beside the NN-plane rows
    try:
        rep.update(bench_serve_quantized())
        if rep.get("serve_quantized_parity") is False:
            raise AssertionError(
                "quantized AOT traversal diverged from the classic "
                "widened-traversal scores — the bit-parity contract of "
                "ops.tree_quant is broken")
    except AssertionError:
        raise
    except Exception as e:                      # pragma: no cover
        rep["serve_quantized_error"] = str(e)[:200]
    # fused raw-record rows: the in-graph transform's overhead acceptance
    try:
        rep.update(bench_serve_raw())
    except AssertionError:
        raise
    except Exception as e:                      # pragma: no cover
        rep["serve_raw_error"] = str(e)[:200]
    # plane guards — fail loudly, like the tail bench's schedule guards
    if recompiles > 0:
        raise AssertionError(
            f"warmed serve plane recompiled {recompiles}x across the "
            "load sweep — request shapes leaked past the bucket ladder "
            "(the exact shape-churn hazard xla.recompiles exists for)")
    slop = float(os.environ.get("SHIFU_BENCH_SERVE_P99_SLOP_MS",
                                SERVE_P99_SLOP_MS))
    if rep["serve_low_p99_ms"] > deadline_ms + slop:
        raise AssertionError(
            f"low-load p99 {rep['serve_low_p99_ms']:.1f} ms exceeds the "
            f"deadline bound {deadline_ms:.1f}+{slop:.0f} ms — the "
            "deadline flush is not bounding tail latency")
    floor = float(os.environ.get("SHIFU_BENCH_SERVE_FLOOR",
                                 SERVE_BENCH_FLOOR))
    if max_ach < floor:
        raise AssertionError(
            f"sustained serve QPS {max_ach:.0f} below the catastrophic "
            f"floor {floor:.0f} (SHIFU_BENCH_SERVE_FLOOR)")
    if traced_qps < TRACE_OVERHEAD_FLOOR_FRAC * floor:
        raise AssertionError(
            f"serve QPS with {TRACE_BENCH_SAMPLE_RATE:.0%} request "
            f"tracing fell to {traced_qps:.0f} — below "
            f"{TRACE_OVERHEAD_FLOOR_FRAC}x the {floor:.0f} floor; "
            "head sampling is no longer bounding tracing overhead")
    return rep


# fused raw-record acceptance: the raw path runs the WHOLE norm
# transform in-graph ahead of the ensemble inside one executable, and
# must hold this fraction of the pre-binned saturation rate — the
# transform must stay a fused prelude, not a second model
SERVE_RAW_FLOOR_FRAC = 0.8


def _raw_bench_configs(n_features: int):
    """Synthetic ZSCALE ColumnConfigs for the raw/fleet serving rows."""
    from shifu_tpu.config import ColumnConfig
    ccs = []
    for j in range(n_features):
        cc = ColumnConfig(columnNum=j, columnName=f"f{j}",
                          finalSelect=True)
        cc.columnBinning.binBoundary = [float("-inf"), -0.5, 0.0, 0.5]
        cc.columnBinning.binCountNeg = [10, 10, 10, 10]
        cc.columnBinning.binCountPos = [2, 4, 6, 8]
        cc.columnBinning.binPosRate = [1 / 6., 2 / 7., 3 / 8., 4 / 9.]
        cc.columnBinning.binCountWoe = [0.1, -0.1, 0.2, -0.2, 0.0]
        cc.columnStats.mean = 0.0
        cc.columnStats.stdDev = 1.0
        ccs.append(cc)
    return ccs


def bench_serve_raw(n_features: int = 32, n_models: int = 5,
                    hidden: tuple = (128, 64), batch: int = 512,
                    duration_s: float = 0.5) -> Dict[str, Any]:
    """Fused raw-record rows (merged into the serve plane): device
    throughput of ``score_batch_raw`` — searchsorted binning + table
    gathers + z-score clip fused AHEAD of the ensemble in the same
    executable — vs the pre-binned ``score_batch`` on the same warmed
    bucket.  ``serve_raw_qps_frac`` (tracked via the ``*_qps_frac``
    throughput suffix) must hold SERVE_RAW_FLOOR_FRAC."""
    import os

    import jax

    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                     init_params)
    from shifu_tpu.serve.scorer import AOTScorer
    from shifu_tpu.serve.transform import FusedTransform

    tf = FusedTransform(ModelConfig(), _raw_bench_configs(n_features))
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                       activations=["relu"] * len(hidden), output_dim=1)
    models = [IndependentNNModel(spec,
                                 init_params(jax.random.PRNGKey(i), spec))
              for i in range(n_models)]
    scorer = AOTScorer(models, buckets=(batch,), transform=tf,
                       name="bench.serve.raw")
    rng = np.random.default_rng(3)
    x = rng.normal(size=(batch, n_features)).astype(np.float32)
    c = tf.n_columns
    packed = np.zeros((batch, tf.wire_width), tf.wire_dtype)
    packed[:, :c] = x
    packed[:, c:2 * c] = 1.0

    def rate(fn, arg):
        fn(arg)                             # compile + warm off the clock
        t0 = time.perf_counter()
        n = 0
        while time.perf_counter() - t0 < duration_s:
            fn(arg)
            n += batch
        return n / (time.perf_counter() - t0)

    pre = rate(scorer.score_batch, x)
    raw = rate(scorer.score_batch_raw, packed)
    frac = raw / max(pre, 1e-9)
    rep = {
        "serve_raw_qps": round(raw, 1),
        "serve_prebinned_qps": round(pre, 1),
        "serve_raw_qps_frac": round(frac, 4),
    }
    floor = float(os.environ.get("SHIFU_BENCH_SERVE_RAW_FLOOR",
                                 SERVE_RAW_FLOOR_FRAC))
    if frac < floor:
        raise AssertionError(
            f"fused raw-record scoring holds only {frac:.2f}x the "
            f"pre-binned rate (floor {floor}, "
            "SHIFU_BENCH_SERVE_RAW_FLOOR) — the in-graph transform "
            "prelude is taxing the scorer it was fused into")
    return rep


# the fleet's closed-loop clients are deadline-bound ON PURPOSE: each
# client thread keeps exactly one request in flight, so most of every
# request is maxDelayMs deadline wait and aggregate QPS measures how
# many replicas the router keeps concurrently busy — near-linear
# replica scaling is observable without N cores
FLEET_DEADLINE_MS = 40.0
FLEET_SCALING_FLOOR = 0.8


def _fleet_modelset(n_features: int, n_models: int, hidden: tuple) -> str:
    """Scratch model-set dir (config snapshot + models) fleet workers
    load — the raw path end to end, subprocess boundary included."""
    import os
    import tempfile

    import jax

    from shifu_tpu.config import save_column_configs
    from shifu_tpu.config.model_config import ModelConfig
    from shifu_tpu.models.nn import NNModelSpec, init_params, save_model

    d = tempfile.mkdtemp(prefix="shifu-bench-fleet-")
    ModelConfig().save(os.path.join(d, "ModelConfig.json"))
    save_column_configs(_raw_bench_configs(n_features),
                        os.path.join(d, "ColumnConfig.json"))
    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                       activations=["relu"] * len(hidden), output_dim=1)
    os.makedirs(os.path.join(d, "models"))
    for i in range(n_models):
        save_model(os.path.join(d, "models", f"model{i}.nn"), spec,
                   init_params(jax.random.PRNGKey(i), spec))
    return d


def _fleet_up(model_set_dir: str, n: int):
    """n subprocess serve workers + a router balancing over them."""
    import os

    from shifu_tpu.serve.router import (ServeRouter, spawn_worker,
                                        wait_for_announce)

    fleet_dir = os.path.join(model_set_dir, "serving", "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    router = ServeRouter(poll_ms=250.0, stale_s=10.0)
    started = []
    for i in range(n):
        ann = os.path.join(fleet_dir, f"bench-{n}r-{i}.json")
        if os.path.exists(ann):
            os.unlink(ann)
        started.append((f"r{i}", ann,
                        spawn_worker(model_set_dir, f"r{i}", ann,
                                     max_delay_ms=FLEET_DEADLINE_MS)))
    for name, ann, p in started:
        doc = wait_for_announce(ann, p, timeout=300.0)
        router.add_backend(name, doc["port"], proc=p)
    router.poll_once()
    router.ensure_uniform()
    return router, [p for _, _, p in started]


def _fleet_closed_loop(router, record: dict, n_threads: int,
                       duration_s: float, kill=None):
    """Closed-loop clients through the router; returns
    ``(qps, latencies, failures)``.  ``kill=(proc, at_frac)`` SIGKILLs
    that worker mid-window — the replica-death drill: the router must
    requeue, so ``failures`` staying empty IS the acceptance."""
    import threading

    lats: list = []
    failures: list = []
    lock = threading.Lock()
    stop = threading.Event()

    def client():
        while not stop.is_set():
            t0 = time.perf_counter()
            try:
                router.score({"records": [record]}, timeout=30.0)
            except RuntimeError as e:
                with lock:
                    failures.append(str(e))
                continue
            dt = time.perf_counter() - t0
            with lock:
                lats.append(dt)

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    if kill is not None:
        proc, at_frac = kill
        time.sleep(duration_s * at_frac)
        proc.kill()
        time.sleep(duration_s * (1.0 - at_frac))
    else:
        time.sleep(duration_s)
    wall = time.perf_counter() - t0
    stop.set()
    for t in threads:
        t.join(timeout=30.0)
    return len(lats) / wall, lats, failures


def bench_fleet(n_features: int = 8, n_models: int = 3,
                hidden: tuple = (16,), duration_s: float = 4.0
                ) -> Dict[str, Any]:
    """Serving-fleet plane (``bench.py --plane fleet``): subprocess
    worker fleets of 1/2/4 replicas behind
    :class:`~shifu_tpu.serve.router.ServeRouter`, each driven by one
    closed-loop raw-record client per replica (deadline-bound — see
    FLEET_DEADLINE_MS).  Reports aggregate QPS per fleet width, the
    2-replica scaling acceptance ``serve_fleet_scaling_frac`` =
    qps_2r / (2 x qps_1r) (tracked via the ``*_scaling_frac`` suffix;
    floor FLEET_SCALING_FLOOR == the >=1.6x aggregate criterion), and
    the replica-death drill on the widest fleet: one worker SIGKILLed
    mid-window, EVERY accepted request completes by requeue and the
    p99 under the kill rides the lower-is-better latency class."""
    import os
    import shutil

    d = _fleet_modelset(n_features, n_models, hidden)
    record = {f"f{j}": round(float(j) / n_features - 0.4, 3)
              for j in range(n_features)}
    rep: Dict[str, Any] = {}
    qps: Dict[int, float] = {}
    try:
        for n in (1, 2, 4):
            router, procs = _fleet_up(d, n)
            try:
                q, lats, failures = _fleet_closed_loop(
                    router, record, n_threads=n, duration_s=duration_s)
                if failures:
                    raise AssertionError(
                        f"{len(failures)} fleet request(s) failed with "
                        f"every replica live: {failures[0]}")
                qps[n] = q
                rep[f"serve_fleet_{n}r_qps"] = round(q, 1)
                rep[f"serve_fleet_{n}r_p99_ms"] = round(
                    float(np.percentile(lats, 99)) * 1000.0, 3)
                if n == 4:
                    kq, klats, kfail = _fleet_closed_loop(
                        router, record, n_threads=n,
                        duration_s=duration_s, kill=(procs[0], 0.4))
                    if kfail:
                        raise AssertionError(
                            f"{len(kfail)} request(s) lost across the "
                            "replica SIGKILL — requeue-on-replica-death "
                            f"failed: {kfail[0]}")
                    survivors = router.poll_once()["up"]
                    rep["serve_fleet_kill_qps"] = round(kq, 1)
                    rep["serve_fleet_kill_p99_ms"] = round(
                        float(np.percentile(klats, 99)) * 1000.0, 3)
                    rep["serve_fleet_kill_survivors"] = int(survivors)
                    if survivors >= n:
                        raise AssertionError(
                            "SIGKILLed replica still counted up — the "
                            "router never noticed the death")
            finally:
                router.stop()
        scaling = qps[2] / max(2.0 * qps[1], 1e-9)
        rep["serve_fleet_scaling_frac"] = round(scaling, 4)
        rep["serve_fleet_shape"] = (
            f"{n_models} NN models {n_features}->{list(hidden)}->1, "
            f"subprocess workers, deadline {FLEET_DEADLINE_MS:.0f} ms, "
            f"1 closed-loop raw-record client/replica, "
            f"{duration_s:.0f}s windows")
        floor = float(os.environ.get("SHIFU_BENCH_FLEET_SCALING",
                                     FLEET_SCALING_FLOOR))
        if scaling < floor:
            raise AssertionError(
                f"2-replica fleet holds {qps[2]:.0f} QPS vs {qps[1]:.0f} "
                f"single-replica — scaling {scaling:.2f} below {floor} "
                "(SHIFU_BENCH_FLEET_SCALING; the >=1.6x aggregate-QPS "
                "acceptance)")
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return rep


# overload-plane acceptance: goodput at 2x the measured saturation must
# hold this fraction of the saturation QPS (SHIFU_BENCH_OVERLOAD_FLOOR
# overrides) — bounded admission + deadline sheds exist precisely so
# excess offered load costs ~nothing, instead of collapsing throughput
OVERLOAD_GOODPUT_FLOOR = 0.8
# per-request budget while the overload windows run; the admission cap
# is sized so queue wait alone cannot eat more than ~half of it
OVERLOAD_DEADLINE_MS = 150.0


def _serve_overload_load(batcher, pool: np.ndarray, qps: float,
                         duration_s: float) -> Dict[str, Any]:
    """Shed-tolerant open-loop client: same ideal-schedule arrivals as
    :func:`_serve_open_loop`, but admission rejects (429-class) are
    counted instead of fatal and deadline sheds surface as coded
    :class:`DeadlineExceededError` at ``wait()``.  A ``TimeoutError``
    is a HUNG client — the failure mode the overload plane exists to
    rule out — and is counted separately so the guard can demand zero."""
    from shifu_tpu.serve.overload import (DeadlineExceededError,
                                          OverloadedError)
    clock = batcher.clock
    n_target = int(qps * duration_s)
    period = 1.0 / qps
    pool_n = len(pool)
    tickets, sent, rejected = [], 0, 0
    t0 = clock()
    while sent < n_target:
        due = min(n_target, int((clock() - t0) / period) + 1)
        if due <= sent:
            time.sleep(0.0002)
            continue
        idx = np.arange(sent, due)
        try:
            tickets.append(batcher.submit_burst(pool[idx % pool_n],
                                                stamps=t0 + idx * period))
        except OverloadedError:
            rejected += len(idx)
        sent = due
    ok_lats, expired, hung = [], 0, 0
    for t in tickets:
        try:
            t.wait(30.0)
            ok_lats.append(t.latencies())
        except DeadlineExceededError:
            expired += t.n
        except TimeoutError:
            hung += t.n
    wall = clock() - t0
    completed = int(sum(len(ls) for ls in ok_lats))
    return {
        "offered": n_target, "rejected": int(rejected),
        "expired": int(expired), "hung": int(hung),
        "completed": completed, "goodput": completed / wall,
        "lats": (np.concatenate(ok_lats) if ok_lats
                 else np.zeros(0, np.float64)),
    }


def bench_overload(n_features: int = 32, n_models: int = 5,
                   hidden: tuple = (64,),
                   duration_s: float = 0.8) -> Dict[str, Any]:
    """Overload-protection plane (``bench.py --plane overload``): the
    serve plane's saturation QPS is measured unprotected, then the
    admission cap (``maxQueueRows`` sized to ~half the deadline of queue
    runway) and a per-request deadline are armed and the SAME server is
    driven at 1x / 2x / 4x of that saturation by shed-tolerant open-loop
    clients.

    Saturation is measured with the SAME open-loop client the windows
    use (unprotected, overdriven at the pipelined ceiling), so the
    denominator isolates the protection penalty from client-pattern
    differences.  Headline ``serve_overload_goodput`` = completed-
    request QPS at the 2x window, tracked via the ``*_goodput``
    throughput suffix and guarded >= ``SHIFU_BENCH_OVERLOAD_FLOOR`` x
    the saturation QPS — under bounded admission, doubling offered
    load may shed half the requests but must NOT collapse the rate of
    answered ones.
    ``serve_overload_p99_ms`` is the p99 of ADMITTED requests (the
    lower-is-better latency class): under overload the meaningful tail
    is the one clients who got answers saw; shed requests fast-fail
    with coded errors and are counted in ``serve_overload_shed_frac``.
    Three more guards: zero hung clients (every ticket resolves with a
    score or a coded error), zero recompiles after warm, and the 4x
    window must actually shed (a cap that never binds tests nothing)."""
    import os

    import jax

    from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                     init_params)
    from shifu_tpu.serve import ServeServer, serve_recompile_count

    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                       activations=["relu"] * len(hidden), output_dim=1)
    models = [IndependentNNModel(spec,
                                 init_params(jax.random.PRNGKey(i), spec))
              for i in range(n_models)]
    server = ServeServer(models=models, key="bench").start()
    batcher = server.batcher
    scorer = server.registry.get("bench")
    rng = np.random.default_rng(0)
    pool = rng.normal(size=(4096, n_features)).astype(np.float32)
    try:
        for n in (1, 3, *scorer.buckets):
            batcher.score_sync(pool[:n])
        # pipelined ceiling (4 bursts outstanding, client blocked in
        # wait): only the OVERDRIVE rate for the saturation window below
        pipe_qps, _ = _serve_saturation(batcher, pool, duration_s / 2)
        # the real denominator: what the SAME open-loop client drains
        # with no deadline, overdriven past the pipelined ceiling.  A
        # small queue bound (8 flushes of runway) keeps the client
        # shedding and submitting for the WHOLE window — an unbounded
        # queue would absorb the excess as backlog and then drain it
        # after the client went quiet, inflating the denominator with
        # interference-free QPS the protected windows never see
        batcher.max_queue_rows = 8 * batcher._top_bucket()
        batcher.default_deadline_s = 0.0
        sat = _serve_overload_load(batcher, pool, pipe_qps,
                                   duration_s)["goodput"]
        recompiles0 = serve_recompile_count()
        sheds0 = batcher.stats["shed_overload"] + \
            batcher.stats["shed_expired"]
        # arm the protection on the live batcher: queue runway = half
        # the deadline at the measured drain rate (so queue wait alone
        # can never eat the whole budget), deadline = the window knob
        deadline_s = OVERLOAD_DEADLINE_MS / 1000.0
        batcher.max_queue_rows = max(batcher._top_bucket(),
                                     int(sat * deadline_s / 2.0))
        batcher.default_deadline_s = deadline_s
        import gc
        gc_was_enabled = gc.isenabled()
        gc.disable()
        try:
            res = {m: _serve_overload_load(batcher, pool, m * sat,
                                           duration_s)
                   for m in (1, 2, 4)}
        finally:
            if gc_was_enabled:
                gc.enable()
        recompiles = serve_recompile_count() - recompiles0
        sheds = batcher.stats["shed_overload"] + \
            batcher.stats["shed_expired"] - sheds0
    finally:
        server.stop()

    def shed_frac(r):
        return (r["rejected"] + r["expired"]) / max(r["offered"], 1)

    r2 = res[2]
    rep: Dict[str, Any] = {
        "serve_overload_sat_qps_offered": round(sat, 1),
        "serve_overload_pipeline_qps_offered": round(pipe_qps, 1),
        "serve_overload_goodput": round(r2["goodput"], 1),
        "serve_overload_goodput_1x": round(res[1]["goodput"], 1),
        "serve_overload_goodput_4x": round(res[4]["goodput"], 1),
        "serve_overload_shed_frac": round(shed_frac(r2), 4),
        "serve_overload_shed_frac_4x": round(shed_frac(res[4]), 4),
        "serve_overload_p99_ms": round(
            float(np.percentile(r2["lats"], 99)) * 1000.0, 3)
        if len(r2["lats"]) else 0.0,
        "serve_overload_hung": sum(r["hung"] for r in res.values()),
        "serve_overload_deadline_ms": OVERLOAD_DEADLINE_MS,
        "serve_overload_max_queue_rows": int(batcher.max_queue_rows),
        "serve_recompiles_after_warm": int(recompiles),
        "serve_overload_sheds": int(sheds),
        "serve_overload_shape": f"{n_models} NN models {n_features}->"
                                f"{list(hidden)}->1, open-loop 1x/2x/4x "
                                f"of saturation, deadline "
                                f"{OVERLOAD_DEADLINE_MS:.0f} ms, "
                                f"{duration_s:.1f}s windows",
    }
    if rep["serve_overload_hung"]:
        raise AssertionError(
            f"{rep['serve_overload_hung']} overload-window request(s) "
            "hung past the 30s client timeout — a shed MUST resolve its "
            "ticket with a coded error, never leave the client waiting")
    if recompiles > 0:
        raise AssertionError(
            f"warmed serve plane recompiled {recompiles}x across the "
            "overload windows — shedding must not perturb the bucket "
            "ladder")
    if shed_frac(res[4]) <= 0.0:
        raise AssertionError(
            "4x offered load shed nothing — the admission cap never "
            "bound, so the overload plane measured a no-op")
    floor = float(os.environ.get("SHIFU_BENCH_OVERLOAD_FLOOR",
                                 OVERLOAD_GOODPUT_FLOOR))
    if r2["goodput"] < floor * sat:
        raise AssertionError(
            f"goodput at 2x offered load is {r2['goodput']:.0f} QPS vs "
            f"{sat:.0f} saturation — below the {floor} floor "
            "(SHIFU_BENCH_OVERLOAD_FLOOR); overload is collapsing "
            "throughput instead of shedding it")
    return rep


# the score-log bench runs the same head-sampling rate as the trace
# bench; scorelog-on QPS must hold this fraction of the scorelog-off
# saturation QPS (the v11 overhead acceptance)
SCORELOG_BENCH_SAMPLE_RATE = 0.01
SCORELOG_OVERHEAD_FLOOR_FRAC = 0.95
# detect-phase joined-batch size; min_joined stays the knob default (64)
QUALITY_DETECT_BATCH = 64


def bench_quality(n_features: int = 32, n_models: int = 3,
                  hidden: tuple = (64,), duration_s: float = 0.6
                  ) -> Dict[str, Any]:
    """Model-quality observability plane (``bench.py --plane quality``):
    two acceptances —

    - **score-log overhead**: saturation QPS with the serve-path score
      log OFF (the default) vs ON at a 1% head-sampling rate into a
      scratch model-set dir; ``serve_scorelog_qps_frac`` (on/off,
      tracked by ``--compare`` via the ``*_qps_frac`` suffix) must stay
      >= SCORELOG_OVERHEAD_FLOOR_FRAC — sampled logging must not tax
      the serving plane it observes;
    - **time-to-detect**: a :class:`~shifu_tpu.obs.quality.
      QualityMonitor` seeded with a synthetic posttrain snapshot is fed
      label-FLIPPED joined outcomes in QUALITY_DETECT_BATCH-row batches
      until its verdict turns degraded; ``quality_label_flip_detect_s``
      (wall, tracked LOWER-is-better via the ``*_detect_s`` suffix) is
      the streaming monitor's detection latency at bench scale."""
    import os
    import shutil
    import tempfile

    import jax

    from shifu_tpu.eval.metrics import auc_trapezoid, sweep
    from shifu_tpu.models.nn import (IndependentNNModel, NNModelSpec,
                                     init_params)
    from shifu_tpu.obs.quality import QualityMonitor
    from shifu_tpu.obs.scorelog import read_score_records, scorelog_dir
    from shifu_tpu.serve import ServeServer

    spec = NNModelSpec(input_dim=n_features, hidden_nodes=list(hidden),
                       activations=["relu"] * len(hidden), output_dim=1)
    models = [IndependentNNModel(spec,
                                 init_params(jax.random.PRNGKey(i), spec))
              for i in range(n_models)]
    rng = np.random.default_rng(0)
    pool = rng.normal(size=(4096, n_features)).astype(np.float32)

    def saturate(server) -> float:
        batcher = server.batcher
        try:
            # warm every bucket before the measured window
            for n in (1, 3, *server.registry.get("bench").buckets):
                batcher.score_sync(pool[:n])
            qps, _ = _serve_saturation(batcher, pool, duration_s)
        finally:
            server.stop()
        return qps

    off_qps = saturate(ServeServer(models=models, key="bench").start())
    scratch = tempfile.mkdtemp(prefix="shifu_bench_quality_")
    try:
        on_qps = saturate(ServeServer(
            models=models, key="bench", model_set_dir=scratch,
            scorelog_sample_rate=SCORELOG_BENCH_SAMPLE_RATE).start())
        logged = len(read_score_records(scorelog_dir(scratch)))
    finally:
        shutil.rmtree(scratch, ignore_errors=True)
    frac = on_qps / max(off_qps, 1e-9)

    # ---- detect phase: well-separated synthetic baseline, then the
    # live stream joins the SAME scores against FLIPPED labels
    n_base = 4096
    labels = (rng.random(n_base) < 0.5).astype(np.float64)
    scores = np.clip(np.where(labels > 0.5,
                              rng.normal(700.0, 120.0, n_base),
                              rng.normal(300.0, 120.0, n_base)),
                     0.0, 1000.0)
    c = sweep(scores, labels)
    base_auc = float(auc_trapezoid(c.fp / c.neg_total,
                                   c.tp / c.pos_total))
    from shifu_tpu.obs.quality import write_posttrain_snapshot
    snap_dir = tempfile.mkdtemp(prefix="shifu_bench_snap_")
    try:
        snap = write_posttrain_snapshot(
            os.path.join(snap_dir, "posttrain.json"), scores,
            auc=base_auc)
    finally:
        shutil.rmtree(snap_dir, ignore_errors=True)
    mon = QualityMonitor(snapshot=snap)
    t0 = time.perf_counter()
    detect_s = None
    fed = 0
    while fed < n_base:
        sl = slice(fed, fed + QUALITY_DETECT_BATCH)
        mon.observe_scores(1, scores[sl])
        mon.update(1, scores[sl], 1.0 - labels[sl])    # the label flip
        fed += len(scores[sl])
        if mon.summary()["degraded"]:
            detect_s = time.perf_counter() - t0
            break
    if detect_s is None:
        raise AssertionError(
            f"quality monitor never flagged a FULL label flip over "
            f"{n_base} joined rows (baseline AUC {base_auc:.3f}) — the "
            "live-AUC trigger is dead")

    rep: Dict[str, Any] = {
        "serve_scorelog_off_qps": round(off_qps, 1),
        "serve_scorelog_on_qps": round(on_qps, 1),
        "serve_scorelog_qps_frac": round(frac, 4),
        "serve_scorelog_sample_rate": SCORELOG_BENCH_SAMPLE_RATE,
        "serve_scorelog_records": int(logged),
        "quality_label_flip_detect_s": round(detect_s, 4),
        "quality_label_flip_detect_rows": int(fed),
        "quality_baseline_auc": round(base_auc, 4),
        "quality_shape": f"{n_models} NN models {n_features}->"
                         f"{list(hidden)}->1, pool 4096 rows, scorelog "
                         f"{SCORELOG_BENCH_SAMPLE_RATE:.0%} sampled, "
                         f"detect batches of {QUALITY_DETECT_BATCH}",
    }
    if frac < SCORELOG_OVERHEAD_FLOOR_FRAC:
        raise AssertionError(
            f"saturation QPS with the score log on fell to {frac:.3f}x "
            f"the scorelog-off rate ({on_qps:.0f} vs {off_qps:.0f}) — "
            f"below {SCORELOG_OVERHEAD_FLOOR_FRAC}x; sampled score "
            "logging is taxing the serve plane it observes")
    return rep


# --------------------------------------------------------------- compare
# `bench.py --compare OLD.json NEW.json [--threshold 0.9]`: the
# BENCH_r01..r05 trajectory exists in-repo but nothing read it — this is
# the reader.  Diffs two bench payloads metric-by-metric and exits 2
# when any TRACKED THROUGHPUT metric fell below threshold x old, so a
# perf regression fails CI instead of quietly becoming the new normal.

def load_bench_file(path: str) -> Dict[str, Any]:
    """A bench payload from either shape on disk: the raw JSON line
    ``bench.py`` prints, or the driver's BENCH_r0N wrapper (``{"n", ...,
    "parsed": {...}}``)."""
    import json
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    if not isinstance(doc, dict) or "metric" not in doc:
        raise ValueError(f"{path} is not a bench payload "
                         "(no 'metric' key)")
    return doc


def bench_multihost(rows: int = 8192, features: int = 16,
                    epochs: int = 6, kill_step: int = 3
                    ) -> Dict[str, Any]:
    """Elastic multi-controller plane (``bench.py --plane multihost``):
    the quorum-gated streamed NN job (parallel/elastic) measured two
    ways —

    - **scaling curve**: the SAME global dataset trained by 1, 2 and 4
      controller processes (each owning 1/N of the rows; the per-epoch
      combine rides the ``telemetry/steps/`` control plane), reported
      as global rows*epochs per second of the slowest controller
      (``multihost_{1,2,4}p_rows_per_sec``, tracked by ``--compare``)
      plus scaling efficiency vs the 1-process run;
    - **time-to-recover**: a 2-controller quorum-mode run
      (quorumFrac 0.97, 2 s step timeout) where one controller is
      SIGKILL-equivalently killed at an injected ``dcn:step`` boundary;
      the survivor finishes under quorum, the controller is relaunched,
      and ``multihost_recover_s`` is relaunch → rejoined-and-finished
      wall (journal catch-up + the remaining live steps; tracked
      LOWER-is-better via the ``*_recover_s`` suffix).

    The bench asserts the monitor's verdict of the recover run: every
    controller's final heartbeat is ``exited`` (no permanent straggler
    in the step-lag table) and the rejoiner replayed a non-empty
    committed prefix.  Runs on any backend — the elastic path needs no
    cross-process collectives, which is its point."""
    import json as _json
    import os
    import subprocess
    import sys
    import tempfile

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

    def launch(out: str, proc: int, nproc: int, mode_args, env_extra=None):
        env = dict(os.environ)
        env["PYTHONPATH"] = repo_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        env.setdefault("SHIFU_TPU_HEARTBEAT_S", "0.25")
        env.update(env_extra or {})
        cmd = [sys.executable, "-m", "shifu_tpu.parallel.elastic_demo",
               "--out", out, "--proc", str(proc), "--nproc", str(nproc),
               "--rows", str(rows), "--features", str(features),
               "--epochs", str(epochs)] + list(mode_args)
        return subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True)

    def wait_all(procs, what: str):
        for i, p in enumerate(procs):
            out_txt, _ = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(
                    f"multihost bench: {what} controller {i} failed "
                    f"rc={p.returncode}:\n{out_txt[-2000:]}")

    def result(out: str, proc: int) -> Dict[str, Any]:
        with open(os.path.join(out, f"result-{proc}.json")) as f:
            return _json.load(f)

    sync_args = ["--quorum-frac", "1.0", "--timeout-ms", "120000"]
    extras: Dict[str, Any] = {}
    rates: Dict[int, float] = {}
    with tempfile.TemporaryDirectory(prefix="shifu_mh_bench_") as td:
        # ---- 1 -> 2 -> 4 controller scaling (sync mode: every step
        # waits for every live member, the worst case for the protocol)
        for nproc in (1, 2, 4):
            out = os.path.join(td, f"scale{nproc}")
            wait_all([launch(out, p, nproc, sync_args)
                      for p in range(nproc)], f"{nproc}p")
            slowest = max(result(out, p)["train_s"] for p in range(nproc))
            rates[nproc] = rows * epochs / slowest
            extras[f"multihost_{nproc}p_rows_per_sec"] = round(
                rates[nproc], 1)
        extras["multihost_scaling_eff_2p"] = round(rates[2] / rates[1], 3)
        extras["multihost_scaling_eff_4p"] = round(rates[4] / rates[1], 3)

        # ---- kill one controller mid-train, relaunch, time the recover
        quorum_args = ["--quorum-frac", "0.97", "--timeout-ms", "2000"]
        out = os.path.join(td, "recover")
        survivor = launch(out, 0, 2, quorum_args)
        victim = launch(out, 1, 2, quorum_args,
                        env_extra={"SHIFU_TPU_FAULTS":
                                   f"dcn:step={kill_step}:kill"})
        v_out, _ = victim.communicate(timeout=600)
        if victim.returncode != 137:
            raise RuntimeError(
                "multihost bench: victim controller did not die at the "
                f"injected dcn:step boundary (rc={victim.returncode}):\n"
                + v_out[-2000:])
        t0 = time.perf_counter()
        rejoiner = launch(out, 1, 2, quorum_args)
        wait_all([survivor, rejoiner], "recover")
        recover_s = time.perf_counter() - t0
        rj = result(out, 1)
        if not rj["dcn"]["rejoined"] or rj["dcn"]["catchup_steps"] <= 0:
            raise RuntimeError("multihost bench: relaunched controller "
                               f"did not rejoin from its journal: {rj}")
        extras["multihost_recover_s"] = round(recover_s, 3)
        extras["multihost_recover_catchup_steps"] = \
            rj["dcn"]["catchup_steps"]
        extras["multihost_kill_step"] = kill_step

        # ---- the monitor's verdict: no permanent straggler
        from shifu_tpu.obs.monitor import aggregate_records, step_lag_table
        recs, counts = aggregate_records([out])
        lag = step_lag_table(recs)
        bad = [r["proc"] for r in recs if r["status"] in ("stalled",
                                                          "stale")]
        if bad:
            raise RuntimeError("multihost bench: permanent straggler(s) "
                               f"after the recover run: {bad}")
        extras["multihost_recover_controllers_exited"] = \
            counts.get("exited", 0)
        extras["multihost_step_lag_rows"] = len(lag)
    extras["multihost_shape"] = (f"{rows} rows x {features} feats, "
                                 f"{epochs} epochs, kill at step "
                                 f"{kill_step}")
    return extras


def bench_refresh(n_rows: int = None, drift_rows: int = None,
                  n_trees: int = 24, extra_trees: int = 8
                  ) -> Dict[str, Any]:
    """Continual-refresh plane (``bench.py --plane refresh``): the cost
    of going from "the model is stale" to "a better model is serving".

    One scripted lifecycle on generated fraud data: init→stats→norm→
    train a GBT incumbent, serve it in-process, append a drifted stream
    (amounts scaled 2x) and re-norm, feed the controller's drift monitor
    until PSI breaches, then run ONE warm refresh cycle —
    checkpoint-resumed trees appended on the new data window, AUC gate,
    hot-swap, short probation.  A scoring pump drives real traffic
    through the swap the whole time.

    Reported (``--compare`` tracks the first as LOWER-is-better):

    - ``refresh_time_to_promoted_s``   trigger decision → promote
      decision (retrain + gate + swap; probation excluded);
    - ``refresh_cold_pipeline_s``      the alternative the reference
      pays: stats + norm + train from scratch on the same drifted
      stream;
    - ``refresh_warm_vs_cold``         cold / warm speedup;
    - ``refresh_slo_alerts_during_swap`` MUST be 0 — the serving
      plane's error budget does not page during a promotion.
    """
    import importlib.util
    import os
    import shutil
    import tempfile
    import threading

    # sized so data-proportional work dominates XLA compile on the CPU
    # rig (CI rigs can shrink it via SHIFU_BENCH_REFRESH_ROWS)
    n_rows = n_rows or int(os.environ.get("SHIFU_BENCH_REFRESH_ROWS",
                                          200_000))
    drift_rows = drift_rows or max(n_rows // 4, 1000)

    spec = importlib.util.spec_from_file_location(
        "make_fraud_data",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "examples", "make_fraud_data.py"))
    gen = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(gen)

    from shifu_tpu.config import ModelConfig
    from shifu_tpu.config.model_config import Algorithm
    from shifu_tpu.pipeline.create import InitProcessor, create_new_model
    from shifu_tpu.pipeline.norm import NormalizeProcessor
    from shifu_tpu.pipeline.stats import StatsProcessor
    from shifu_tpu.pipeline.train import TrainProcessor
    from shifu_tpu.refresh import (RefreshConfig, RefreshController,
                                   drift_columns_for)
    from shifu_tpu.serve.server import ServeServer

    def configure(mdir: str, csv: str) -> None:
        mc = ModelConfig.load(os.path.join(mdir, "ModelConfig.json"))
        mc.dataSet.dataPath = csv
        mc.dataSet.dataDelimiter = "|"
        mc.dataSet.targetColumnName = "tag"
        mc.dataSet.posTags = ["bad"]
        mc.dataSet.negTags = ["good"]
        mc.dataSet.weightColumnName = "weight"
        mc.dataSet.metaColumnNameFile = os.path.join(
            os.path.dirname(csv), "meta.names")
        mc.train.algorithm = Algorithm.GBT
        mc.train.params = {"TreeNum": n_trees, "MaxDepth": 4,
                           "Loss": "log", "LearningRate": 0.1,
                           "CheckpointInterval": 8}
        mc.train.baggingNum = 1
        mc.save(os.path.join(mdir, "ModelConfig.json"))

    out: Dict[str, Any] = {"refresh_rows": n_rows,
                           "refresh_drift_rows": drift_rows}
    with tempfile.TemporaryDirectory() as td:
        csv = gen.make(os.path.join(td, "data"), n=n_rows)
        mdir = create_new_model("refresh", base_dir=td)
        configure(mdir, csv)
        assert InitProcessor(mdir).run() == 0
        assert StatsProcessor(mdir, params={}).run() == 0
        assert NormalizeProcessor(mdir, params={}).run() == 0
        assert TrainProcessor(mdir, params={}).run() == 0

        # drifted stream: fresh rows with 2x amounts appended, plane
        # re-materialized (the refresh loop's "new data window")
        drift_csv = gen.make(os.path.join(td, "drift"), n=drift_rows,
                             seed=1234)
        with open(csv) as f:
            n_before = sum(1 for _ in f) - 1
        # appending the drifted stream to the bench's own generated
        # dataset — an input fixture, not a pipeline artifact
        with open(drift_csv) as src, \
                open(csv, "a") as dst:  # shifu-lint: disable=atomic-write
            next(src)                                   # header
            for i, line in enumerate(src):
                parts = line.rstrip("\n").split("|")
                parts[0] = f"d{i}"
                if parts[1]:
                    parts[1] = f"{float(parts[1]) * 2.0:.4f}"
                dst.write("|".join(parts) + "\n")
        assert NormalizeProcessor(mdir, params={}).run() == 0

        # p99 objective sized for the CPU rig's launch cost: the guard
        # is "the SWAP must not burn the budget", not "CPU scoring
        # meets a TPU-sized latency objective"
        server = ServeServer(mdir, buckets=(1, 64), max_delay_ms=1.0,
                             slo_p99_ms=250.0).start()
        try:
            ctrl = RefreshController(
                mdir, server=server,
                config=RefreshConfig(psi_threshold=0.25, cooldown_s=0.0,
                                     probation_s=0.3, units=extra_trees,
                                     canary_rows=32),
                drift_columns=drift_columns_for(mdir))
            # earlier training consumed the pre-drift plane
            from shifu_tpu.data.shards import Shards
            total = Shards.open(os.path.join(mdir, "tmp",
                                             "CleanedData")).num_rows
            cursor = int(total * n_before / (n_before + drift_rows))
            ctrl.journal.set_cursor(cursor)

            # the drifted serving stream: skewed bin windows until the
            # live PSI breaches
            n_cols = len(ctrl._drift.columns)
            skew = np.zeros((512, n_cols), np.int64)
            for _ in range(64):
                ctrl.observe(skew)
                summ = ctrl._drift.summary()
                if (summ["psi_max"] or 0) >= 0.25:
                    break
            out["refresh_trigger_psi"] = round(
                float(ctrl._drift.summary()["psi_max"]), 4)

            # real traffic through the swap
            scorer = server.registry.get(server.key)
            rng = np.random.default_rng(0)
            pump_x = rng.normal(size=(32, scorer.n_features)) \
                .astype(np.float32)
            pump_b = rng.integers(
                0, 2, size=(32, scorer.n_bins_cols)).astype(np.int32) \
                if scorer.needs_bins else None
            stop_pump = threading.Event()

            def pump():
                while not stop_pump.is_set():
                    try:
                        server.score(pump_x, pump_b, timeout=30.0)
                    except Exception:       # noqa: BLE001 — bench pump
                        break

            t = threading.Thread(target=pump, daemon=True)
            t.start()
            t0 = time.perf_counter()
            outcome = ctrl.run_once(poll_s=0.05, timeout_s=600.0)
            warm_total = time.perf_counter() - t0
            stop_pump.set()
            t.join(timeout=10.0)
            if outcome != "promoted":
                raise RuntimeError(
                    f"refresh bench: warm cycle ended {outcome!r}, "
                    "expected a promotion")
            by_kind = {}
            for d in ctrl.journal.decisions():
                by_kind.setdefault(d["kind"], d)
            out["refresh_time_to_promoted_s"] = round(
                by_kind["promote"]["ts"] - by_kind["trigger"]["ts"], 3)
            out["refresh_warm_cycle_s"] = round(warm_total, 3)
            out["refresh_resumed_from_trees"] = \
                by_kind["train"].get("resumed_from", 0)
            out["refresh_warm_start"] = bool(
                by_kind["train"].get("warm"))
            out["refresh_generation"] = server.registry.generation(
                server.key)
            alerts = server.slo.alerts()
            out["refresh_slo_alerts_during_swap"] = len(alerts)
            if alerts:
                raise RuntimeError("refresh bench: the serving SLO "
                                   f"paged during the swap: {alerts}")
            if not out["refresh_warm_start"]:
                raise RuntimeError("refresh bench: the retrain cold-"
                                   "started (no checkpoint restored)")
        finally:
            server.stop()

        # the cold alternative: full stats+norm+train from scratch on
        # the SAME drifted stream (what the reference re-runs)
        cdir = create_new_model("refresh-cold", base_dir=td)
        configure(cdir, csv)
        assert InitProcessor(cdir).run() == 0
        t0 = time.perf_counter()
        assert StatsProcessor(cdir, params={}).run() == 0
        assert NormalizeProcessor(cdir, params={}).run() == 0
        assert TrainProcessor(cdir, params={}).run() == 0
        out["refresh_cold_pipeline_s"] = round(
            time.perf_counter() - t0, 3)
        shutil.rmtree(cdir, ignore_errors=True)
    out["refresh_warm_vs_cold"] = round(
        out["refresh_cold_pipeline_s"]
        / max(out["refresh_time_to_promoted_s"], 1e-9), 3)
    out["refresh_shape"] = (f"{n_rows}+{drift_rows} rows, GBT "
                            f"{n_trees}+{extra_trees} trees depth 4")
    return out


def bench_metrics(doc: Dict[str, Any]) -> Dict[str, float]:
    """Flatten a payload to {metric: value}: the headline plus every
    numeric top-level extra."""
    out: Dict[str, float] = {}
    if isinstance(doc.get("value"), (int, float)):
        out[str(doc["metric"])] = float(doc["value"])
    for k, v in (doc.get("extra") or {}).items():
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            out[str(k)] = float(v)
    return out


def is_tracked_throughput(name: str) -> bool:
    """Higher-is-better metrics gate the compare: throughputs, sustained
    QPS, plus the v6 utilization extras (*_mfu / *_achieved_bw — a drop
    means the same plane is doing the same math slower, exactly what the
    compare exists to catch).  Ratios, shapes and wall-clock extras
    inform but never fail."""
    if name.endswith("_vs_baseline") or name.endswith("_error") \
            or name.endswith("_offered"):
        return False
    return ("throughput" in name or name.endswith("_per_sec")
            or name.endswith("_qps") or name.endswith("_qps_sustained")
            or name.endswith("_qps_frac")
            or name.endswith("_scaling_frac")
            or name.endswith("_goodput")
            or name.endswith("_mfu") or name.endswith("_achieved_bw"))


def is_tracked_latency(name: str) -> bool:
    """LOWER-is-better metrics (v7/v8): latency percentiles plus the
    serve decomposition's queue/pad fractions (time a request spends
    waiting or being padded, not scored — growth is a regression).  A
    serve p99 that grows past old/threshold regresses the compare
    exactly like a throughput drop — tail latency is the serving
    plane's contract.  ``*_device_frac`` stays informational: a larger
    device share usually means LESS overhead, not more."""
    if name.endswith("_error") or name.endswith("_vs_baseline"):
        return False
    return ("_p50" in name or "_p99" in name
            or name.endswith("_queue_frac") or name.endswith("_pad_frac")
            or name.endswith("_recover_s") or name.endswith("_detect_s")
            or name.endswith("_time_to_promoted_s")
            or name.endswith("_wall_s"))


def compare_bench(old: Dict[str, Any], new: Dict[str, Any],
                  threshold: float = 0.9):
    """(rows, regressed): per-metric diff rows sorted tracked-first, and
    the tracked metrics that regressed — higher-is-better metrics when
    new < threshold x old, LOWER-is-better (latency) metrics when
    new > old / threshold."""
    om, nm = bench_metrics(old), bench_metrics(new)
    rows, regressed = [], []
    for name in sorted(set(om) | set(nm),
                       key=lambda n: (not (is_tracked_throughput(n)
                                           or is_tracked_latency(n)), n)):
        ov, nv = om.get(name), nm.get(name)
        lower_better = is_tracked_latency(name)
        tracked = is_tracked_throughput(name) or lower_better
        ratio = (nv / ov) if (ov and nv is not None) else None
        flag = ""
        if tracked and ov and nv is not None and (
                nv > ov / threshold if lower_better
                else nv < threshold * ov):
            flag = "REGRESSED"
            regressed.append(name)
        elif ov is None:
            flag = "new"
        elif nv is None:
            flag = "gone"
        rows.append({"metric": name, "old": ov, "new": nv, "ratio": ratio,
                     "tracked": tracked, "lower_better": lower_better,
                     "flag": flag})
    return rows, regressed


def format_compare_table(rows, threshold: float) -> str:
    def num(v):
        return "-" if v is None else f"{v:,.1f}"
    out = [f"{'metric':<46}{'old':>16}{'new':>16}{'ratio':>8}  flag",
           "-" * 92]
    for r in rows:
        ratio = "-" if r["ratio"] is None else f"{r['ratio']:.3f}"
        mark = "v" if r.get("lower_better") else \
            ("*" if r["tracked"] else " ")
        out.append(f"{mark}{r['metric']:<45}{num(r['old']):>16}"
                   f"{num(r['new']):>16}{ratio:>8}  {r['flag']}")
    out.append(f"(* = tracked throughput metric, v = tracked latency "
               f"metric [lower is better]; REGRESSED = new < "
               f"{threshold} x old, or latency new > old / {threshold})")
    return "\n".join(out)


def resolve_compare_paths(paths, root: str = None):
    """The ``--compare`` arguments resolved to (old, new).  Two explicit
    paths pass through; NONE switches to auto mode: pick the two newest
    ``BENCH_r*.json`` in the repo root (zero-padded round number = name
    order, so "newest" is deterministic regardless of checkout mtimes)
    and diff older -> newer.  Fewer than two on disk is a clear coded
    error, never a traceback."""
    import glob
    import os
    paths = list(paths or [])
    if len(paths) == 2:
        return paths[0], paths[1]
    if paths:
        raise ValueError("--compare takes exactly two payload paths, or "
                         "none to auto-diff the two newest BENCH_r*.json")
    root = root or os.path.dirname(os.path.dirname(os.path.abspath(
        __file__)))
    cands = sorted(glob.glob(os.path.join(root, "BENCH_r*.json")))
    if len(cands) < 2:
        raise ValueError(
            f"--compare auto mode needs at least two BENCH_r*.json under "
            f"{root} (found {len(cands)}) — run the bench twice or pass "
            "OLD.json NEW.json explicitly")
    return cands[-2], cands[-1]


def run_compare(old_path: str, new_path: str,
                threshold: float = 0.9, _print=print) -> int:
    """The `--compare` entry: print the regression table, return the
    exit code (0 clean, 2 = tracked throughput regression)."""
    old, new = load_bench_file(old_path), load_bench_file(new_path)
    rows, regressed = compare_bench(old, new, threshold=threshold)
    _print(f"bench compare: {old_path} -> {new_path} "
           f"(threshold {threshold})")
    _print(format_compare_table(rows, threshold))
    if regressed:
        _print(f"REGRESSION: {len(regressed)} tracked metric(s) below "
               f"{threshold} x old: {', '.join(regressed)}")
        return 2
    _print("no tracked throughput regressions")
    return 0


def _check_schema_handshake() -> None:
    if BENCH_TELEMETRY_SCHEMA != obs.SCHEMA_VERSION:
        raise RuntimeError(
            f"bench telemetry schema v{BENCH_TELEMETRY_SCHEMA} disagrees "
            f"with shifu_tpu.obs SCHEMA_VERSION v{obs.SCHEMA_VERSION} — "
            "update bench.py's per-plane metric emission for the new "
            "schema and bump BENCH_TELEMETRY_SCHEMA")


def run_benchmark(plane: str = None) -> Dict[str, Any]:
    """Full sweep by default; ``plane="tail"`` runs ONLY the disk-tail
    streamed-GBT benchmark (seconds, not minutes) so the out-of-core
    path can be iterated on in isolation."""
    _check_schema_handshake()
    if obs.enabled():
        obs.ensure_compile_listener()
    if plane == "tail":
        with obs.span("bench.gbt_train_throughput_streamed_tail",
                      kind="bench"):
            rep = bench_gbt_streamed_tail()
        v = rep["tail_rows_trees_per_sec"]
        for k, val in rep.items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                obs.gauge(f"bench.{k}").set(float(val))
        obs.gauge("bench.gbt_train_throughput_streamed_tail").set(v)
        obs.gauge("bench.gbt_train_throughput_streamed_tail_vs_baseline") \
            .set(v / BASELINE_TREE_RATE)
        return {
            "metric": "gbt_train_throughput_streamed_tail",
            "value": round(v, 1),
            "unit": "rows*trees/sec",
            "plane": "tail",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "vs_baseline": round(v / BASELINE_TREE_RATE, 3),
            "baseline_rows_per_sec": BASELINE_TREE_RATE,
            "baseline_provenance": "measured 43068.1 rows*trees/s/worker "
                                   "np.add.at hist GBT on this rig x 100 "
                                   "north-star workers (BASELINE.md)",
            "shape": rep["tail_shape"],
            "extra": rep,
        }
    if plane == "rf-repeat":
        with obs.span("bench.rf_repeat", kind="bench"):
            rep = bench_rf_repeat()
        for k, v in rep.items():
            if isinstance(v, (int, float)):
                obs.gauge(f"bench.{k}").set(float(v))
        return {
            "metric": "rf_repeat_warm_median",
            "value": rep["rf_repeat_warm_median"],
            "unit": "rows*trees/sec",
            "plane": "rf-repeat",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "vs_baseline": rep["rf_repeat_warm_median_vs_baseline"],
            "baseline_rows_per_sec": BASELINE_TREE_RATE,
            "extra": rep,
        }
    if plane == "e2e":
        with obs.span("bench.pipeline_e2e", kind="bench"):
            rep = bench_pipeline_e2e()
        for k, v in rep.items():
            if isinstance(v, (int, float)):
                obs.gauge(f"bench.{k}").set(float(v))
        return {
            "metric": "pipeline_e2e_rows_per_sec",
            "value": rep["pipeline_e2e_rows_per_sec"],
            "unit": "rows/sec",
            "plane": "e2e",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "extra": rep,
        }
    if plane == "ingest":
        with obs.span("bench.ingest", kind="bench"):
            rep = bench_ingest()
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
        return {
            "metric": "stats_throughput",
            "value": rep["stats_throughput"],
            "unit": "rows/sec",
            "plane": "ingest",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "extra": rep,
        }
    if plane == "resume":
        with obs.span("bench.resume", kind="bench"):
            rep = bench_resume()
        for k, v in rep.items():
            if isinstance(v, (int, float)):
                obs.gauge(f"bench.resume_{k}").set(float(v))
        return {
            "metric": "resume_first_tree_s",
            "value": rep["resume_first_tree_s"],
            "unit": "seconds",
            "plane": "resume",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "extra": rep,
        }
    if plane == "varsel":
        with obs.span("bench.varsel", kind="bench"):
            rep = bench_varsel()
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
        v = rep["varsel_stream_rows_cols_per_sec"]
        return {
            "metric": "varsel_stream_rows_cols_per_sec",
            "value": v,
            "unit": "rows*cols/sec",
            "plane": "varsel",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "vs_baseline": round(v / BASELINE_VARSEL_RATE, 3),
            "baseline_rows_per_sec": BASELINE_VARSEL_RATE,
            "baseline_provenance": "measured 510610.6 rows*cols/s/worker "
                                   "f64 per-column frozen-forward loop on "
                                   "this rig x 100 north-star workers "
                                   "(BASELINE.md)",
            "extra": rep,
        }
    if plane == "serve":
        with obs.span("bench.serve", kind="bench"):
            rep = bench_serve()
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
        v = rep["serve_qps_sustained"]
        return {
            "metric": "serve_qps_sustained",
            "value": v,
            "unit": "rows/sec",
            "plane": "serve",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "vs_baseline": round(v / BASELINE_SCORE_RATE, 3),
            "baseline_rows_per_sec": BASELINE_SCORE_RATE,
            "baseline_provenance": "measured 1505.9 rows/s/worker per-row "
                                   "bagged scorer on this rig x 100 "
                                   "north-star workers (BASELINE.md)",
            "extra": rep,
        }
    if plane == "fleet":
        with obs.span("bench.fleet", kind="bench"):
            rep = bench_fleet()
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
        return {
            "metric": "serve_fleet_2r_qps",
            "value": rep["serve_fleet_2r_qps"],
            "unit": "requests/sec",
            "plane": "fleet",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "shape": rep["serve_fleet_shape"],
            "extra": rep,
        }
    if plane == "overload":
        with obs.span("bench.overload", kind="bench"):
            rep = bench_overload()
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
        return {
            "metric": "serve_overload_goodput",
            "value": rep["serve_overload_goodput"],
            "unit": "requests/sec",
            "plane": "overload",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "shape": rep["serve_overload_shape"],
            "extra": rep,
        }
    if plane == "multihost":
        with obs.span("bench.multihost", kind="bench"):
            rep = bench_multihost()
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
        return {
            "metric": "multihost_2p_rows_per_sec",
            "value": rep["multihost_2p_rows_per_sec"],
            "unit": "rows*epochs/sec",
            "plane": "multihost",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "shape": rep["multihost_shape"],
            "extra": rep,
        }
    if plane == "refresh":
        with obs.span("bench.refresh", kind="bench"):
            rep = bench_refresh()
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
        return {
            "metric": "refresh_time_to_promoted_s",
            "value": rep["refresh_time_to_promoted_s"],
            "unit": "seconds",
            "plane": "refresh",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "shape": rep["refresh_shape"],
            "extra": rep,
        }
    if plane == "quality":
        with obs.span("bench.quality", kind="bench"):
            rep = bench_quality()
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
        return {
            "metric": "serve_scorelog_qps_frac",
            "value": rep["serve_scorelog_qps_frac"],
            "unit": "ratio",
            "plane": "quality",
            "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
            "shape": rep["quality_shape"],
            "extra": rep,
        }
    if plane not in (None, "all"):
        raise ValueError(
            f"unknown bench plane {plane!r} "
            "(tail|rf-repeat|e2e|ingest|resume|varsel|serve|fleet|"
            "overload|multihost|refresh|quality|all)")
    nn_cost: Dict[str, Any] = {}
    nn_rows_per_sec = bench_nn(collect=nn_cost)
    obs.gauge("bench.nn_train_throughput").set(nn_rows_per_sec)
    extras: Dict[str, Any] = {}
    # utilization extras (schema v6): MFU + achieved bandwidth from the
    # timed executable's own XLA cost analysis — --compare tracks them
    _mfu_extras("nn_train", nn_rows_per_sec, nn_cost, extras)
    for k in ("nn_train_mfu", "nn_train_achieved_bw"):
        if k in extras:
            obs.gauge(f"bench.{k}").set(float(extras[k]))

    def record(key: str, fn, baseline: float) -> None:
        """Every extra carries its own measured-denominator ratio; the
        same numbers flow through the obs registry so BENCH_r0N.json and
        the telemetry JSONL share one schema."""
        try:
            with obs.span(f"bench.{key}", kind="bench"):
                v = fn()
            extras[key] = round(v, 1)
            extras[key + "_vs_baseline"] = round(v / baseline, 3)
            obs.gauge(f"bench.{key}").set(v)
            obs.gauge(f"bench.{key}_vs_baseline").set(v / baseline)
        except Exception as e:                  # pragma: no cover
            extras[key + "_error"] = str(e)[:200]

    # mixed-precision ladder row (same harness/shape as the f32 row so
    # the pair reads as one before/after on the compare table)
    mixed_cost: Dict[str, Any] = {}
    record("nn_train_mixed_throughput",
           lambda: bench_nn_mixed(collect=mixed_cost),
           BASELINE_ROWS_PER_SEC)
    if "nn_train_mixed_throughput" in extras:
        _mfu_extras("nn_train_mixed", extras["nn_train_mixed_throughput"],
                    mixed_cost, extras)
        for k in ("nn_train_mixed_mfu", "nn_train_mixed_achieved_bw"):
            if k in extras:
                obs.gauge(f"bench.{k}").set(float(extras[k]))
    record("gbt_train_throughput_resident", bench_gbt, BASELINE_TREE_RATE)
    record("gbt_train_throughput_streamed", bench_gbt_streamed,
           BASELINE_TREE_RATE)
    try:
        with obs.span("bench.gbt_train_throughput_streamed_tail",
                      kind="bench"):
            tail_rep = bench_gbt_streamed_tail()
        v = tail_rep["tail_rows_trees_per_sec"]
        extras["gbt_train_throughput_streamed_tail"] = v
        extras["gbt_train_throughput_streamed_tail_vs_baseline"] = round(
            v / BASELINE_TREE_RATE, 3)
        extras.update(tail_rep)
        obs.gauge("bench.gbt_train_throughput_streamed_tail").set(v)
        obs.gauge("bench.gbt_train_throughput_streamed_tail_vs_baseline") \
            .set(v / BASELINE_TREE_RATE)
        for k, val in tail_rep.items():
            if isinstance(val, (int, float)) and not isinstance(val, bool):
                obs.gauge(f"bench.{k}").set(float(val))
    except Exception as e:                      # pragma: no cover
        extras["gbt_train_throughput_streamed_tail_error"] = str(e)[:200]
    record("rf_train_throughput", bench_rf, BASELINE_TREE_RATE)
    wdl_cost: Dict[str, Any] = {}
    record("wdl_train_throughput",
           lambda: bench_wdl(collect=wdl_cost), BASELINE_ROWS_PER_SEC)
    if "wdl_train_throughput" in extras:
        _mfu_extras("wdl_train", extras["wdl_train_throughput"], wdl_cost,
                    extras)
        for k in ("wdl_train_mfu", "wdl_train_achieved_bw"):
            if k in extras:
                obs.gauge(f"bench.{k}").set(float(extras[k]))
    wdl_sh_cost: Dict[str, Any] = {}
    record("wdl_train_sharded_throughput",
           lambda: bench_wdl_sharded(collect=wdl_sh_cost),
           BASELINE_ROWS_PER_SEC)
    if "wdl_train_sharded_throughput" in extras:
        _mfu_extras("wdl_train_sharded",
                    extras["wdl_train_sharded_throughput"], wdl_sh_cost,
                    extras)
        if "wdl_train_throughput" in extras:
            extras["wdl_train_sharded_vs_replicated"] = round(
                extras["wdl_train_sharded_throughput"]
                / max(extras["wdl_train_throughput"], 1e-9), 3)
        for k in ("wdl_train_sharded_mfu", "wdl_train_sharded_achieved_bw",
                  "wdl_train_sharded_vs_replicated"):
            if k in extras:
                obs.gauge(f"bench.{k}").set(float(extras[k]))
    record("eval_throughput", bench_eval, BASELINE_SCORE_RATE)
    record("stats_throughput", bench_stats, BASELINE_STATS_RATE)
    try:
        with obs.span("bench.varsel", kind="bench"):
            rep = bench_varsel()
        extras.update(rep)
        extras["varsel_throughput_vs_baseline"] = round(
            rep["varsel_stream_rows_cols_per_sec"] / BASELINE_VARSEL_RATE,
            3)
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
    except Exception as e:                      # pragma: no cover
        extras["varsel_throughput_error"] = str(e)[:200]
    try:
        with obs.span("bench.serve", kind="bench"):
            rep = bench_serve()
        extras.update(rep)
        extras["serve_qps_vs_baseline"] = round(
            rep["serve_qps_sustained"] / BASELINE_SCORE_RATE, 3)
        for k, v in rep.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                obs.gauge(f"bench.{k}").set(float(v))
    except Exception as e:                      # pragma: no cover
        extras["serve_qps_error"] = str(e)[:200]
    extras["streamed_bench_shape"] = {
        "resident": "262144 rows x 100 trees (since r5; was x 8 — 100 = "
                    "the default TreeNum, amortizing the one-time ingest "
                    "a real default train amortizes)",
        "gbt_resident": "131072 rows x 100 trees (since r5; was x 32 — "
                        "100 = the default TreeNum)",
        "tail": "65536 rows x 4 trees, budget forces disk tail (uint8-"
                "resident bins accounting since r6; warm pass builds the "
                "mmap spill cache, tail sweeps re-read it zero-decode; "
                "learnable logit target + dual-schedule c2f/exact "
                "reporting since r9)"}
    extras["baselines"] = {
        "tree_rows_trees_per_sec_per_worker":
            MEASURED_CPU_TREE_ROWS_TREES_PER_SEC,
        "stats_rows_per_sec_per_worker":
            MEASURED_CPU_STATS_ROWS_PER_SEC,
        "score_rows_per_sec_per_worker": MEASURED_CPU_SCORE_ROWS_PER_SEC,
        "cluster_workers": BASELINE_CLUSTER_WORKERS,
        "provenance": "tools/measure_baseline.py on this rig (BASELINE.md)",
    }
    return {
        "metric": "nn_train_throughput",
        "value": round(nn_rows_per_sec, 1),
        "unit": "rows/sec",
        "telemetry_schema_version": BENCH_TELEMETRY_SCHEMA,
        "vs_baseline": round(nn_rows_per_sec / BASELINE_ROWS_PER_SEC, 3),
        "baseline_rows_per_sec": BASELINE_ROWS_PER_SEC,
        "baseline_provenance": "measured 28850.5 rows/s/worker f64 backprop "
                               "on this rig x 100 north-star workers "
                               "(BASELINE.md, tools/measure_baseline.py)",
        # harness re-based mid-round-3: the r01/r02 timing loop synced via
        # block_until_ready, which this device link answers EARLY (phantom
        # readiness) — those numbers were inflated ~4x.  Timing is now a
        # value-forcing fetch around ONE scanned executable per window
        # (steps fused via lax.scan), best of 3 windows; r01/r02 values
        # are not comparable.
        "harness": {"matmul_precision": "bfloat16",
                    "timing": "value-forced, scanned steps; best-of-3 (NN/"
                              "WDL long windows) / best-of-5 (sub-second "
                              "windows — the dev link adds +-20% noise)",
                    "since_round": 3},
        "extra": extras,
    }
