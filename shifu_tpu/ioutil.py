"""Crash-consistent file IO: atomic commits + transient-failure retry.

Two small primitives every artifact writer in the pipeline shares:

- **atomic writes** — content lands in a same-directory temp file and
  ``os.replace``s into place, so a reader (or a resumed run) never
  observes a torn file; the journal/manifest layer decides *commit*
  separately, these helpers only guarantee each file is all-or-nothing.
- **bounded retry with exponential backoff + jitter** — shard reads and
  spill IO ride shared filesystems (GCS fuse, NFS, preemptible local
  SSD) where transient ``OSError``s are weather, not bugs.  ``io_retry``
  absorbs up to ``shifu.io.retries`` of them (telemetry counter
  ``ingest.retries``); the final attempt re-raises with the artifact's
  provenance in the message so the operator knows *which* shard died.
"""

from __future__ import annotations

import contextlib
import io
import json
import logging
import os
import random
import time
from typing import Any, Callable, Iterator, TypeVar

import numpy as np

log = logging.getLogger(__name__)

T = TypeVar("T")


def _retries() -> int:
    from .config import environment
    return max(0, environment.get_int("shifu.io.retries", 3))


def _retry_base_s() -> float:
    from .config import environment
    return environment.get_int("shifu.io.retryBaseMs", 50) / 1000.0


def io_retry(fn: Callable[[], T], what: str, path: str = "") -> T:
    """Run ``fn``, absorbing transient ``OSError``s with exponential
    backoff + jitter.  The final failure re-raises the original error
    wrapped with provenance (``what`` + ``path``)."""
    attempts = _retries() + 1
    base = _retry_base_s()
    for attempt in range(attempts):
        try:
            return fn()
        except OSError as e:
            if attempt + 1 >= attempts:
                raise OSError(
                    f"{what} failed after {attempts} attempt(s)"
                    f"{f' [{path}]' if path else ''}: {e}") from e
            from . import obs
            # retry loop only spins on transient IO weather — the
            # factory lookup here is as cold as the backoff sleep
            obs.counter("ingest.retries").inc()  # shifu-lint: disable=telemetry-guard
            delay = base * (2 ** attempt) * (1.0 + random.random())
            log.warning("transient IO error in %s%s (attempt %d/%d, "
                        "retrying in %.0f ms): %s", what,
                        f" [{path}]" if path else "", attempt + 1,
                        attempts, delay * 1000, e)
            time.sleep(delay)
    raise AssertionError("unreachable")


def _tmp_path(path: str) -> str:
    return f"{path}.tmp{os.getpid()}"


def atomic_write_bytes(path: str, data: bytes) -> None:
    tmp = _tmp_path(path)
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


def atomic_write_json(path: str, obj: Any, indent: int = 2) -> None:
    atomic_write_text(path, json.dumps(obj, indent=indent))


def atomic_savez(path: str, **arrays: np.ndarray) -> None:
    """npz written whole-or-not-at-all (np.savez writing directly to the
    final path leaves a torn zip on a crash mid-write)."""
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    atomic_write_bytes(path, buf.getvalue())


def atomic_save_npy(path: str, arr: np.ndarray) -> None:
    """Single-array ``.npy`` twin of :func:`atomic_savez`."""
    buf = io.BytesIO()
    np.save(buf, arr)
    atomic_write_bytes(path, buf.getvalue())


@contextlib.contextmanager
def atomic_open(path: str, mode: str = "w", **kwargs) -> Iterator[Any]:
    """Streaming writer with the tmp+``os.replace`` discipline: yields a
    file object positioned at a same-directory temp file; a clean exit
    commits it into place, an exception unlinks the temp (the final path
    is never observed half-written).  For artifact writers that stream
    too much to buffer (score CSVs, PMML) — small payloads should use
    :func:`atomic_write_text`/``_json``/``_bytes`` directly."""
    if "r" in mode or "a" in mode or "+" in mode:
        raise ValueError(f"atomic_open is write-only (mode={mode!r})")
    tmp = _tmp_path(path)
    f = open(tmp, mode, **kwargs)
    try:
        yield f
    except BaseException:
        f.close()
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    else:
        f.close()
        os.replace(tmp, path)


def sweep_orphan_tmp(directory: str) -> int:
    """Remove ``*.tmp<pid>`` droppings a previous crash left next to the
    artifacts.  Returns the number removed (best-effort)."""
    n = 0
    try:
        entries = os.listdir(directory)
    except OSError:
        return 0
    for f in entries:
        stem, tmp, pid = f.rpartition(".tmp")
        if tmp and pid.isdigit():
            try:
                os.remove(os.path.join(directory, f))
                n += 1
            except OSError:
                pass
    return n
